"""BaseModule — the high-level train/predict lifecycle (reference
python/mxnet/module/base_module.py: bind → init_params → init_optimizer →
fit/forward_backward/update/score/predict)."""
from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as metric_mod
from ..base import MXNetError
from ..io import DataBatch
from ..model import BatchEndParam
from ..ndarray import NDArray, concatenate
from ..initializer import Uniform

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def _check_input_names(symbol, names, typename, throw):
    args = symbol.list_arguments()
    for name in names:
        if name not in args:
            msg = "You created Module with Module(..., %s_names=%s) but " \
                  "input with name '%s' is not found in symbol.list_arguments(). " \
                  "Did you mean one of:\n\t%s\n" % (
                      typename, str(names), name, "\n\t".join(args))
            if throw:
                raise ValueError(msg)
            logging.warning(msg)


class BaseModule(object):
    """reference base_module.py:BaseModule."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high level API ----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run prediction on eval_data and evaluate (base_module.py:score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect predictions (base_module.py:predict)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches, as num of outputs is not the same " \
                    "in mini-batches. Maybe bucketing is used?"
            output_list2 = [concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint=None, resume=False,
            preemption_safe=None, watchdog=None):
        """The canonical training loop (reference base_module.py:368-520).

        ``checkpoint`` (a :class:`~mxnet_tpu.resilience.CheckpointManager`
        or a directory path) turns on managed epoch-end checkpointing:
        params + optimizer state land atomically after every epoch, with
        retention handled by the manager.  ``resume=True`` (or the
        ``MXTPU_RESUME=1`` env set by ``tools/supervise.py`` relaunches)
        restores the newest checkpoint before training — params,
        optimizer state and epoch — so a preempted run relaunched with
        the same arguments continues where it stopped.  A MID-EPOCH
        checkpoint (saved by graceful preemption, below) additionally
        carries step + RNG state: the resumed run fast-forwards the data
        iterator past the consumed batches and restores the random
        stream, making the relaunch bit-identical to the uninterrupted
        run (the iterator must be deterministic across ``reset()``, which
        every built-in iterator is).

        ``preemption_safe=True`` (or ``MXTPU_ON_PREEMPT=save``) installs
        a SIGTERM/SIGINT handler: the signal sets a flag, the next step
        boundary saves a mid-epoch checkpoint and exits with
        ``resilience.PREEMPT_EXIT_CODE`` — preemption costs at most one
        step of work, not an epoch.  Needs ``checkpoint=``.

        ``watchdog`` arms a hung-step monitor around every batch:
        ``True`` / a :class:`~mxnet_tpu.resilience.StepWatchdog`
        instance, or None to follow the ``MXTPU_STEP_TIMEOUT`` env
        (seconds, or ``auto`` to calibrate from the first steps'
        median).  An overrunning step dumps all thread stacks + device
        state (stderr and ``MXTPU_DEBUG_DIR``) and aborts with
        ``resilience.WATCHDOG_EXIT_CODE`` so a supervisor relaunches
        with resume instead of burning a pod on a wedged collective.

        Async pipeline: ``train_data`` may yield
        :class:`~mxnet_tpu.io.StagedBatch` objects (wrap it in
        ``dataflow.DevicePrefetchIter`` after ``init_optimizer``) to
        overlap the host->device transfer with the running step; on fused
        modules the train metric is accumulated in-graph (deferred — see
        MXTPU_METRIC_INTERVAL / MXTPU_METRIC_BLOCKING) and
        MXTPU_PROFILE_DIR captures a ``jax.profiler`` trace of steps
        10-15 of the first epoch.  See docs/how_to/performance.md."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..base import get_env
        from .. import resilience
        from ..resilience import (CheckpointManager, PreemptionHandler,
                                  StepWatchdog, faults, preempted_exit)

        if checkpoint is not None and not hasattr(checkpoint, "restore"):
            checkpoint = CheckpointManager(checkpoint)
        if not resume and str(get_env(resilience.ENV_RESUME, "0")) == "1":
            # a supervise.py relaunch: same command line, resume forced
            resume = checkpoint is not None
        restored_states = None
        resume_step_state = None
        if resume:
            assert checkpoint is not None, "fit(resume=True) needs checkpoint="
            if checkpoint.latest() is not None:
                _, arg_restored, aux_restored, restored_states, ck_epoch = \
                    checkpoint.restore()
                arg_params, aux_params = arg_restored, aux_restored
                entry = checkpoint.entry(ck_epoch) or {}
                resume_step_state = entry.get("step_state")
                if resume_step_state is not None:
                    # partial (preemption) checkpoint: re-enter the
                    # interrupted epoch, not the one after it
                    begin_epoch = max(begin_epoch,
                                      int(resume_step_state["epoch"]))
                else:
                    begin_epoch = max(begin_epoch, ck_epoch)
                force_init = True
                self.logger.info("fit(resume=True): restored checkpoint "
                                 "epoch %d%s from %s", ck_epoch,
                                 " (mid-epoch, step %d)"
                                 % resume_step_state["step"]
                                 if resume_step_state else "",
                                 checkpoint.directory)

        if preemption_safe is None:
            preemption_safe = checkpoint is not None and str(
                get_env(resilience.ENV_ON_PREEMPT, "")).lower() in \
                ("save", "1")
        if preemption_safe and checkpoint is None:
            raise MXNetError("fit(preemption_safe=True) needs checkpoint= "
                             "(there is nowhere to save the mid-epoch "
                             "state)")

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if restored_states is not None:
            try:
                self.set_optimizer_states(restored_states)
            except NotImplementedError:
                # module can't carry optimizer state (mirrors the save
                # side): resume params + epoch only
                self.logger.warning(
                    "fit(resume=True): %s has no optimizer-state support; "
                    "resuming params and epoch only",
                    type(self).__name__)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        # deferred metrics: fused modules fold the train metric's
        # (sum, count) INTO the step program so update_metric never forces
        # a per-step device->host sync (installed before the first step,
        # so the one compile already includes the accumulators; no-op on
        # the executor path / unsupported metrics / MXTPU_METRIC_BLOCKING)
        self._install_deferred_metric(eval_metric)

        # mid-epoch resume: restore the RNG stream the interrupted run
        # saved at the preemption boundary (AFTER init — the restore must
        # win over anything initialization consumed) and remember how many
        # batches of begin_epoch to fast-forward past
        fast_forward = 0
        if resume_step_state is not None:
            fast_forward = int(resume_step_state.get("step", 0))
            if resume_step_state.get("rng") is not None:
                from .. import random as _random
                _random.set_state(resume_step_state["rng"])

        from contextlib import nullcontext

        # graceful preemption + hung-step watchdog + profiler trace are
        # all set up INSIDE the try so a failure anywhere in bring-up
        # still runs the finally — a leaked signal handler would swallow
        # the process's next Ctrl-C, a leaked monitor thread its memory,
        # a leaked running trace the next fit()'s start_trace
        preempt = None
        wd = None
        own_watchdog = False
        fused_trainer = self._deferred_metric_trainer()
        trace = None
        try:
            if preemption_safe:
                # flag set by SIGTERM/SIGINT, consumed at the step
                # boundaries below.  Multi-process runs AGREE on the flag
                # at each boundary (distributed.agree_flag) so every rank
                # checkpoints at the same step instead of deadlocking in
                # mismatched collectives.
                preempt = PreemptionHandler(logger=self.logger).install()
            import jax as _jax
            preempt_sync = preempt is not None and _jax.process_count() > 1

            # fit owns the watchdog's monitor thread; the fused trainer
            # (when present) is armed too so its per-step context lands
            # in the hang report
            if watchdog is None:
                watchdog = resilience.step_timeout_configured()
            if isinstance(watchdog, StepWatchdog):
                wd = watchdog
            elif watchdog:
                wd = StepWatchdog(logger=self.logger)
                own_watchdog = True
            if wd is not None:
                wd.start()
                if fused_trainer is not None:
                    fused_trainer.install_watchdog(wd)

            # MXTPU_PROFILE_DIR: capture a jax.profiler trace of steps
            # 10-15 of the first epoch (None when the env is unset)
            from .. import profiler as _profiler
            trace = _profiler.StepTraceCapture.from_env()

            ############################################################
            # training loop
            ############################################################
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                data_stream = iter(train_data)
                nbatch = -1
                if epoch == begin_epoch and fast_forward > 0:
                    # fast-forward past the batches the interrupted run
                    # already trained on (deterministic iterators replay
                    # the same order after reset)
                    for _ in range(fast_forward):
                        try:
                            next(data_stream)
                        except StopIteration:
                            break
                        nbatch += 1
                    self.logger.info(
                        "fit(resume=True): fast-forwarded %d batches of "
                        "epoch %d", nbatch + 1, epoch)
                while True:
                    # the armed window covers the data fetch too — a
                    # wedged staging thread hangs the consumer in next()
                    with wd.armed("epoch %d batch %d"
                                  % (epoch, nbatch + 1)) \
                            if wd is not None else nullcontext():
                        try:
                            data_batch = next(data_stream)
                        except StopIteration:
                            break
                        nbatch += 1
                        if trace is not None:
                            trace.on_batch(nbatch)
                        if monitor is not None:
                            monitor.tic()
                        self.forward_backward(data_batch)
                        self.update()
                        self.update_metric(eval_metric, data_batch.label)
                        if monitor is not None:
                            monitor.toc_print()
                        if batch_end_callback is not None:
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(batch_end_params)
                    # step boundary: consume a pending preemption —
                    # checkpoint mid-epoch and exit cleanly for the
                    # supervisor to relaunch with resume
                    if preempt is not None:
                        if faults.consume("preempt"):
                            # in-band drill: deliver a REAL signal so the
                            # whole handler path is what gets tested
                            import os as _os
                            import signal as _signal
                            _os.kill(_os.getpid(), _signal.SIGTERM)
                            time.sleep(0.05)  # let the handler run
                        triggered = preempt.triggered
                        if preempt_sync:
                            # all ranks take the same branch at the same
                            # boundary (any rank signaled => all save)
                            from .. import distributed as _dist
                            triggered = _dist.agree_flag(triggered)
                        if triggered:
                            self._save_preemption_checkpoint(
                                checkpoint, epoch, nbatch + 1)
                            preempted_exit()
                if trace is not None:
                    trace.stop()  # epoch shorter than the window: close
                    trace = None  # first epoch only

                # one epoch of training is finished
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                toc = time.time()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 (toc - tic))

                # sync aux params across devices
                arg_params_, aux_params_ = self.get_params()
                self.set_params(arg_params_, aux_params_)

                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params_,
                                 aux_params_)

                if checkpoint is not None:
                    # gather happens above on EVERY rank (collective under
                    # sharded params); the manager then writes on rank 0
                    # only
                    try:
                        states = self.get_optimizer_states()
                    except NotImplementedError:
                        states = None
                    checkpoint.save(epoch + 1, self.symbol, arg_params_,
                                    aux_params_, optimizer_states=states)

                # ----------------------------------------
                # evaluation on validation set
                if eval_data:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)

                # end of 1 epoch, reset the data-iter for another epoch
                train_data.reset()
            # drain the async checkpoint writers so every epoch's save is
            # durable (and any background write failure surfaces here)
            # before fit() reports success — the manager's own writer AND
            # the shared default writer behind prefix-based saves
            # (epoch_end_callback=do_checkpoint(prefix) queues there; the
            # writer thread is a daemon, so an undrained write could be
            # killed mid-flight at interpreter exit)
            if checkpoint is not None and hasattr(checkpoint, "wait"):
                checkpoint.wait()
            from ..resilience import wait_checkpoints
            wait_checkpoints()
        finally:
            if trace is not None:
                trace.stop()
            if preempt is not None:
                preempt.uninstall()
            if wd is not None:
                if fused_trainer is not None:
                    fused_trainer.install_watchdog(None)
                if own_watchdog:
                    wd.stop()

    def _save_preemption_checkpoint(self, checkpoint, epoch, step):
        """Mid-epoch checkpoint at a step boundary: params + optimizer
        state under the SAME epoch number the epoch-end save will use
        (epoch + 1), plus a ``step_state`` manifest record — epoch index,
        batches consumed, RNG stream — that ``fit(resume=True)`` uses to
        fast-forward.  The later epoch-end save of the same number
        replaces the partial entry.

        The exit-85 contract requires the checkpoint to be ON DISK when
        the process exits: any in-flight async save is drained first
        (best-effort — this blocking save supersedes whatever the failed
        write would have published) and the preemption save itself is
        always blocking, MXTPU_CKPT_ASYNC notwithstanding."""
        from .. import random as _random
        from ..resilience import CheckpointManager, wait_checkpoints
        # BOUNDED drain of the shared default writer (prefix-based async
        # saves): a wedged — not failed — background write must not eat
        # the whole preemption grace period; a timeout surfaces as the
        # same MXNetError a failed write would.  The manager's own
        # writer is drained inside save(blocking=True) below, equally
        # bounded; the blocking save supersedes whatever was in flight.
        try:
            wait_checkpoints(timeout=CheckpointManager.DRAIN_TIMEOUT / 2)
        except Exception as e:  # noqa: BLE001 — superseded below
            self.logger.warning(
                "preemption: in-flight async checkpoint write failed "
                "(%s: %s) — the blocking preemption save below "
                "supersedes it", type(e).__name__, e)
        arg_params_, aux_params_ = self.get_params()
        try:
            states = self.get_optimizer_states()
        except NotImplementedError:
            states = None
        checkpoint.save(epoch + 1, self.symbol, arg_params_, aux_params_,
                        optimizer_states=states, blocking=True,
                        step_state={"epoch": int(epoch), "step": int(step),
                                    "rng": _random.get_state()})
        from ..resilience import PREEMPT_EXIT_CODE
        self.logger.warning(
            "preemption: saved mid-epoch checkpoint (epoch %d, step %d) "
            "to %s; exiting with code %d — relaunch with resume to "
            "continue", epoch, step, checkpoint.directory,
            PREEMPT_EXIT_CODE)

    # -- symbol / params ---------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        from .. import ndarray as nd
        from ..resilience import atomic_path
        with atomic_path(fname) as tmp:
            nd.save(tmp, save_dict)

    def load_params(self, fname):
        from .. import ndarray as nd
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def _deferred_metric_trainer(self):
        """The fused SPMDTrainer that can carry in-graph metrics, or None
        — the base has none, so every module type stays on the classic
        blocking path unless it overrides this."""
        return None

    def _install_deferred_metric(self, eval_metric):
        """fit() hook: move the train metric's accumulation into the
        fused step program (metric.try_install_deferred).  Detaches any
        previously installed metric and uninstalls a stale in-graph rule
        when the new metric cannot defer, so a second fit() never leaks
        the first run's accumulators or steals its deltas."""
        from .. import metric as metric_mod
        prev = getattr(self, "_deferred_metric", None)
        if prev is not None:
            prev.detach_deferred_source()
        self._deferred_metric = None
        self._deferred_interval = 0
        self._deferred_calls = 0
        trainer = self._deferred_metric_trainer()
        if trainer is None:
            return
        interval = metric_mod.try_install_deferred(trainer, eval_metric)
        if interval is None:
            if getattr(trainer, "_metric_fn", None) is not None:
                trainer.install_metric(None)
            return
        self._deferred_metric = eval_metric
        self._deferred_interval = interval

    def _deferred_metric_update(self, eval_metric):
        """True when ``eval_metric`` is accumulated in-graph for train
        steps (the per-step host update must be skipped); folds the
        device totals every ``_deferred_interval`` calls."""
        if getattr(self, "_deferred_metric", None) is not eval_metric:
            return False
        self._deferred_calls += 1
        if self._deferred_interval > 0 and \
                self._deferred_calls % self._deferred_interval == 0:
            eval_metric.fold_deferred()
        return True

    def get_optimizer_states(self):
        """Serialized optimizer state (bytes), for managed checkpointing.
        Subclasses with an optimizer implement this; the base raises so
        ``fit(checkpoint=...)`` degrades to params-only checkpoints."""
        raise NotImplementedError

    def set_optimizer_states(self, states):
        raise NotImplementedError

    # -- abstract interface ------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def install_monitor(self, mon):
        raise NotImplementedError
