"""KVStore — parameter synchronization.

Re-design of the reference KVStore stack (include/mxnet/kvstore.h,
src/kvstore/): ``local``/``device`` are single-process stores aggregating
gradients across device copies (the reference's CommCPU/CommDevice tree
reduction, src/kvstore/comm.h); ``dist_sync``/``tpu`` replace the entire
ps-lite parameter-server column with XLA collectives over ICI/DCN
(SURVEY §2.3 mapping note): the optimizer folds into a psum-based sharded
update step (see parallel/ and kvstore 'tpu' in kvstore_dist.py) instead of
running on remote server processes.

API parity: create/init/push/pull/set_optimizer/rank/num_workers/barrier/
save_optimizer_states/load_optimizer_states (python/mxnet/kvstore.py).
"""
from __future__ import annotations

import pickle

from .base import MXNetError
from .ndarray import NDArray, zeros
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key_value(keys, vals):
    if isinstance(keys, (int, str)):
        if isinstance(vals, NDArray):
            return [keys], [[vals]]
        return [keys], [list(vals)]
    assert len(keys) == len(vals)
    out_keys, out_vals = [], []
    for k, v in zip(keys, vals):
        ks, vs = _key_value(k, v)
        out_keys += ks
        out_vals += vs
    return out_keys, out_vals


class KVStore(object):
    """Single-process store: 'local' (reduce on primary device) and 'device'
    (reduce stays on the data's devices) — observable behavior matches
    src/kvstore/kvstore_local.h."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def init(self, key, value):
        keys, vals = _key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate values (sum over device copies) into the store; if an
        updater is set, run it on the merged gradient (kvstore_local.h Push)."""
        keys, vals = _key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            # bring all device copies to the store's device before reducing
            # (the reference's CommCPU copies to pinned CPU, comm.h:120-179)
            store_ctx = self._store[k].context
            merged = vlist[0].as_in_context(store_ctx).copy()
            for v in vlist[1:]:
                merged += v.as_in_context(store_ctx)
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._data = merged._data
    def pull(self, key, out=None, priority=0):
        assert out is not None
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    def set_optimizer(self, optimizer):
        """Install the optimizer as the store-side updater — the analog of
        pickling the optimizer to dist servers (kvstore.py:set_optimizer)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        pass

    barrier = _barrier

    def get_optimizer_states(self):
        if self._updater is None:
            raise MXNetError("updater is not set")
        return self._updater.get_states()

    def set_optimizer_states(self, states):
        if self._updater is None:
            raise MXNetError("updater is not set")
        self._updater.set_states(states)

    def save_optimizer_states(self, fname):
        # temp + fsync + rename: a crash mid-save can never tear an
        # existing optimizer-state file (same contract as checkpoints)
        from .resilience import atomic_write
        atomic_write(fname, self.get_optimizer_states())

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    def _send_command_to_servers(self, head, body):
        pass

    def get_num_dead_node(self, node_id=-1, timeout=60):
        """Failure-detection stance (the reference's ps-lite heartbeat
        query, kvstore_dist.h:158-167, exposed uniformly on every store):

        XLA collectives over ICI/DCN are synchronous SPMD — liveness is
        all-or-nothing.  A dead worker does not degrade the cluster into a
        smaller one (as a dead ps-lite server shard might); it fails the
        next collective, the JAX distributed runtime surfaces the error on
        every rank, and the job restarts from the last checkpoint (the
        reference's practical recovery is the same: --load-epoch relaunch,
        example fit.py:25-35).  A process able to ask this question is
        therefore in a cluster with zero dead nodes; partial-failure
        probing has no ICI analog.  Elastic resize = relaunch with a new
        process count and resharded checkpoint, outside the kvstore's
        scope.  Single-process stores trivially report 0 as well.
        """
        return 0

    @property
    def is_recovery(self):
        """Restart-detection analog of ps::Postoffice::is_recovery
        (kvstore_dist.h:39-42): always False — restarted TPU jobs rejoin
        as a fresh cluster and resume from checkpoints, they do not
        re-enter a live one."""
        return False


def _updater_key(k):
    return k if isinstance(k, int) else str(k)


def create(name="local"):
    """Factory (reference src/kvstore/kvstore.cc:17-44 name dispatch):
    'local'/'device' → in-process store; 'dist_sync'/'dist_device_sync'/'tpu'
    → collective store over the jax distributed runtime."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist" in name or name == "tpu":
        from .kvstore_dist import KVStoreTPU
        return KVStoreTPU(name)
    return KVStore(name)
