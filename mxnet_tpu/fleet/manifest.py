"""Fleet manifest: WHAT the fleet serves and WHERE each model lives.

The manifest is the routing front end's source of truth (the Clipper
model-abstraction split: the router knows models and policies, never
weights).  It names the models (checkpoint targets + per-sample input
shapes, the exact ``tools/serve.py`` spec format), the replica count,
the bucket set, and the device placement spec; from it the controller
derives each replica's launch command and the router derives each
model's HOME replica.

Placement model: EVERY replica loads EVERY model (the warm pool is
replicated — cheap, because the AOT warm store means a replica warms
from disk, not from XLA), but each model has one stable **home**
replica (its position in the sorted name list mod the replica count)
that takes its traffic by default.  Routing to a home maximizes cache
and batch locality — requests for one model concentrate where its
buckets stay hot — while the replicated pool means SPILL needs no model
loading: when the home's queue crosses the bar, any replica can take
the overflow immediately (docs/how_to/fleet.md).
"""
from __future__ import annotations

import json
import os
import sys

from ..base import MXNetError, get_env, register_env

__all__ = ["FleetManifest", "replica_device_env", "parse_shape_specs",
           "ENV_FLEET_REPLICAS"]

ENV_FLEET_REPLICAS = register_env(
    "MXTPU_FLEET_REPLICAS", default=2,
    doc="Default replica-daemon count for `tools/fleet.py serve` when "
        "the manifest/--replicas does not say otherwise")


def parse_shape_specs(specs):
    """``["mlp:data=784", "data=3,32,32"]`` -> ``{model_or_None:
    {input: shape}}`` — the ``tools/serve.py --input-shape`` format (no
    model prefix = applies to every model)."""
    out = {}
    for spec in specs or ():
        model = None
        head, _, tail = str(spec).partition("=")
        if ":" in head:
            model, _, head = head.partition(":")
        try:
            shape = tuple(int(x) for x in tail.split(",") if x)
        except ValueError:
            raise MXNetError("bad --input-shape spec %r" % (spec,))
        if not head or not shape:
            raise MXNetError("bad --input-shape spec %r (want "
                             "[MODEL:]INPUT=D1,D2,...)" % (spec,))
        out.setdefault(model, {})[head] = shape
    return out


def replica_device_env(device_sets, index):
    """Device pinning for replica ``index`` -> env-overlay dict.

    ``device_sets``:

    - ``None``/``""`` — inherit the parent environment untouched.
    - ``"cpu"`` — every replica runs the CPU backend
      (``JAX_PLATFORMS=cpu``); core partitioning is the controller's
      ``cpu_affinity`` job.
    - ``"tpu:0,1;2,3"`` — ``JAX_PLATFORMS=tpu`` and replica *i* sees
      only chip set ``i % n_sets`` (``TPU_VISIBLE_CHIPS``, plus the
      single-process topology bounds libtpu wants for a 1-chip set) —
      the one-serving-process-per-chip-subset topology.  More replicas
      than sets wrap around (co-tenant replicas on one subset).
    """
    if not device_sets:
        return {}
    if device_sets == "cpu":
        return {"JAX_PLATFORMS": "cpu"}
    plat, _, rest = str(device_sets).partition(":")
    groups = [g.strip() for g in rest.split(";") if g.strip()]
    if plat != "tpu" or not groups:
        raise MXNetError(
            "bad device-sets spec %r (want 'cpu' or 'tpu:0,1;2,3')"
            % (device_sets,))
    chips = groups[index % len(groups)]
    env = {"JAX_PLATFORMS": "tpu", "TPU_VISIBLE_CHIPS": chips}
    if len(chips.split(",")) == 1:
        # a single-chip replica is its own 1x1x1 topology; without the
        # bounds libtpu assumes the whole host's slice is present
        env["TPU_PROCESS_BOUNDS"] = "1,1,1"
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
    return env


class FleetManifest(object):
    """models: ``{name: {"target": "prefix:epoch"|"ckpt-dir",
    "shapes": {input: shape} | None}}`` + replicas/buckets/device_sets.
    """

    def __init__(self, models, replicas=None, buckets=None,
                 device_sets=None, router_workers=None):
        if not models:
            raise MXNetError("a fleet manifest needs at least one model")
        self.models = {}
        for name, spec in models.items():
            if isinstance(spec, str):
                spec = {"target": spec}
            target = spec.get("target")
            if not name or not target:
                raise MXNetError("bad model spec %r=%r (want name -> "
                                 "{'target': prefix:epoch|dir})"
                                 % (name, spec))
            shapes = spec.get("shapes") or None
            if shapes:
                shapes = {k: tuple(int(d) for d in v)
                          for k, v in shapes.items()}
            self.models[name] = {"target": target, "shapes": shapes}
        self.replicas = int(get_env(ENV_FLEET_REPLICAS)
                            if replicas is None else replicas)
        if self.replicas < 1:
            raise MXNetError("replicas must be >= 1, got %d"
                             % self.replicas)
        self.buckets = buckets
        self.device_sets = device_sets
        #: router worker processes sharing the public port (the sharded
        #: front end); None = the MXTPU_FLEET_WORKERS default at serve
        #: time, 1 = the in-line single-process router
        self.router_workers = None if router_workers is None \
            else int(router_workers)
        if self.router_workers is not None and self.router_workers < 1:
            raise MXNetError("router_workers must be >= 1, got %d"
                             % self.router_workers)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_flags(cls, model_flags, shape_flags=(), replicas=None,
                   buckets=None, device_sets=None):
        """The ``tools/serve.py`` flag formats: ``--model
        name=prefix:epoch|name=dir`` (repeatable) + ``--input-shape
        [MODEL:]INPUT=D1,D2`` (repeatable)."""
        shapes = parse_shape_specs(shape_flags)
        models = {}
        for spec in model_flags or ():
            name, _, target = str(spec).partition("=")
            if not name or not target:
                raise MXNetError("bad --model spec %r (want "
                                 "name=prefix:epoch or name=ckpt-dir)"
                                 % (spec,))
            models[name] = {"target": target,
                            "shapes": shapes.get(name, shapes.get(None))}
        return cls(models, replicas=replicas, buckets=buckets,
                   device_sets=device_sets)

    @classmethod
    def from_file(cls, path):
        with open(path) as f:
            doc = json.load(f)
        return cls(doc.get("models") or {},
                   replicas=doc.get("replicas"),
                   buckets=doc.get("buckets"),
                   device_sets=doc.get("device_sets"),
                   router_workers=doc.get("router_workers"))

    def to_doc(self):
        return {"models": {n: {"target": s["target"],
                               "shapes": {k: list(v) for k, v in
                                          (s["shapes"] or {}).items()}
                               or None}
                           for n, s in self.models.items()},
                "replicas": self.replicas,
                "buckets": self.buckets,
                "device_sets": self.device_sets,
                "router_workers": self.router_workers}

    def save(self, path):
        from ..resilience import atomic_write
        atomic_write(path, json.dumps(self.to_doc(), indent=2,
                                      sort_keys=True).encode("utf-8"))
        return path

    # -- routing geometry --------------------------------------------------
    def names(self):
        return sorted(self.models)

    def home(self, model):
        """The model's HOME replica index: stable position in the
        sorted name list mod the replica count — every router instance
        computes the same homes with no coordination."""
        if model not in self.models:
            raise MXNetError("no model %r in the fleet manifest "
                             "(have: %s)" % (model, self.names()))
        return self.names().index(model) % self.replicas

    # -- launch plumbing ---------------------------------------------------
    def serve_argv(self, serve_py, port_file=None, port=0, python=None,
                   warmup=True, warmup_only=False, export_aot=False,
                   extra=()):
        """The ``tools/serve.py`` command line for ONE replica (every
        replica serves the whole manifest — the replicated warm pool).
        ``export_aot`` makes it the warm-store BUILDER instead."""
        argv = [python or sys.executable, serve_py, "--port", str(port)]
        if port_file:
            argv += ["--port-file", port_file]
        if self.buckets:
            argv += ["--buckets", str(self.buckets)]
        for name in self.names():
            spec = self.models[name]
            argv += ["--model", "%s=%s" % (name, spec["target"])]
            for inp, shape in (spec["shapes"] or {}).items():
                argv += ["--input-shape", "%s:%s=%s"
                         % (name, inp, ",".join(str(d) for d in shape))]
        if warmup_only:
            argv += ["--warmup-only"]
        elif warmup:
            argv += ["--warmup"]
        if export_aot:
            argv += ["--export-aot"]
        argv += list(extra)
        return argv


def default_serve_py():
    """``tools/serve.py`` next to this checkout (the replica binary)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "serve.py")
