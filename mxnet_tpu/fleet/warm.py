"""AOT warm store: kill replica cold-start by pre-compiling every
(model, bucket) forward into the persistent compile cache.

A fresh (or respawned) replica's dominant bring-up cost is building one
forward per (model, bucket) pair — Python trace + lowering + XLA
compile, per process.  Those programs are a pure function of (graph,
bucket shape, platform), so the fleet builds them ONCE, ahead of
traffic: the builder compiles each one and serializes the COMPILED
EXECUTABLE into ``<store>/aot/`` (``serving/aot.py`` —
``jax.experimental.serialize_executable``, weight-free artifacts), and
the store directory doubles as every replica's ``MXTPU_COMPILE_CACHE``
(the PR-2 persistent cache catches any program the AOT layer misses).
A replica launched with the store warms by DESERIALIZING executables —
no trace, no lower, no compile.

The store is built by the same binary that serves — ONE
``tools/serve.py --warmup-only --export-aot`` run over the whole
manifest — so the stored programs are exactly the forwards a replica
runs (same eval graph, same platform, same shapes; bit-parity between
the AOT and Predictor paths is pinned in tests/test_serving.py).
``bench.py fleet`` measures the effect as ``fleet_warm_start_x``
(cold-compile vs from-store bring-up; the >= 3x acceptance bar) rather
than assuming it.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import time

from ..base import MXNetError
from .manifest import default_serve_py, replica_device_env

__all__ = ["build_warm_store", "warm_store_manifest", "MARKER"]

#: the store's marker file: records what was warmed (and doubles as the
#: "already built" sentinel for `fleet serve --warm-store`)
MARKER = "FLEET_WARM.json"

WARMUP_RE = re.compile(r"mxserve: warmup_s=([0-9.]+)")


def warm_store_manifest(store_dir):
    """The store's marker doc, or None when the store is absent/unbuilt."""
    path = os.path.join(store_dir, MARKER)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_warm_store(manifest, store_dir, serve_py=None, python=None,
                     timeout=1800.0, force=False, extra_env=None,
                     log=None):
    """Populate ``store_dir`` with every (model, bucket) compiled
    forward; returns the marker doc (with ``warmup_s``, the measured
    cold-compile time — the number a warm replica later avoids).

    Idempotent: an already-built store returns its marker unless
    ``force``.  Raises :class:`MXNetError` when the warmup run fails.
    """
    log = log or (lambda msg: None)
    existing = warm_store_manifest(store_dir)
    if existing is not None and not force:
        log("fleet: warm store %r already built (%d models)"
            % (store_dir, len(existing.get("models", []))))
        return existing
    os.makedirs(store_dir, exist_ok=True)
    argv = manifest.serve_argv(serve_py or default_serve_py(),
                               port_file=None, port=0, python=python,
                               warmup_only=True, export_aot=True)
    env = dict(os.environ)
    # the store must hold the REPLICA platform's programs: warm under
    # replica 0's device env (all replicas share one platform)
    env.update(replica_device_env(manifest.device_sets, 0))
    env.update(extra_env or {})
    env["MXTPU_COMPILE_CACHE"] = store_dir
    log("fleet: building warm store %r (%s)"
        % (store_dir, ", ".join(manifest.names())))
    tic = time.monotonic()
    try:
        res = subprocess.run(argv, env=env, capture_output=True,
                             text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        raise MXNetError("warm-store build exceeded %.0fs" % timeout)
    if res.returncode != 0:
        raise MXNetError("warm-store build failed (rc %d):\n%s"
                         % (res.returncode, (res.stderr or "")[-2000:]))
    m = WARMUP_RE.search(res.stderr or "")
    warmup_s = float(m.group(1)) if m else round(
        time.monotonic() - tic, 3)
    doc = {"models": manifest.names(),
           "buckets": manifest.buckets,
           "device_sets": manifest.device_sets,
           "warmup_s": warmup_s,
           "built_unix": time.time()}
    from ..resilience import atomic_write
    atomic_write(os.path.join(store_dir, MARKER),
                 json.dumps(doc, indent=2, sort_keys=True)
                 .encode("utf-8"))
    log("fleet: warm store built in %.2fs" % warmup_s)
    return doc
