"""Replica autoscaling: a controller-side loop that ACTS on the
signals the router already aggregates.

PR 11 left the fleet observable but static: the router's probe loop
collects every replica's ``est_wait_ms`` and queue depths, mxswap owns
a safe way to take a replica out (fence -> drain -> stop), and the AOT
warm store makes bring-up ~0.5s — but nobody closed the loop.  The
:class:`Autoscaler` does, with deliberately boring policy:

- **signal**: mean over healthy replicas of each replica's WORST
  per-model ``est_wait_ms`` (the batcher's own wait estimate — the
  same number the spill policy trusts).  Injectable (``signal_fn``)
  so policy tests drive a synthetic square wave instead of a fleet.
- **hysteresis**: the signal must sit above ``high_ms`` for
  ``up_after`` consecutive ticks to scale up, below ``low_ms`` for
  ``down_after`` ticks to scale down; the band between the watermarks
  does nothing and resets neither streak's opposite.  A chaos drill
  bouncing one replica produces a spike, not a flap.
- **cooldown**: after ANY action, no further action for
  ``cooldown_s`` — scale-up takes ~0.5s + warmup to absorb load, and
  judging the new capacity with the old signal would double-scale.
- **scale-up** = :meth:`ReplicaController.add_replica` (warm via the
  AOT store, joins routing when its port file appears and a probe
  lands).
- **scale-down** = the mxswap safety dance, then retirement: fence the
  victim at the PROBER router (the capacity floor check lives in
  ``fence`` — at the floor the fence raises and the tick just counts
  ``blocked_floor``), publish so every front-end worker stops routing
  to it, wait out its queue, then
  :meth:`ReplicaController.stop_replica` (SIGTERM -> the replica
  drains its accepted work to rc 0 — the mxserve contract) and
  unfence the retired id.  Victim = the highest-id healthy replica,
  so the boot-time replicas (with their CPU pinning and manifest
  homes) are the last to go.

The loop never drops below ``min_replicas`` and never grows past
``max_replicas`` — and independently of ``min_replicas``, the fence's
own N-1 floor means scale-down can NEVER take the last routable
replica.
"""
from __future__ import annotations

import threading
import time

from ..base import MXNetError, get_env, register_env

__all__ = ["Autoscaler", "ENV_FLEET_SCALE_HIGH_MS",
           "ENV_FLEET_SCALE_LOW_MS", "ENV_FLEET_SCALE_COOLDOWN_S",
           "ENV_FLEET_MIN_REPLICAS", "ENV_FLEET_MAX_REPLICAS"]

ENV_FLEET_SCALE_HIGH_MS = register_env(
    "MXTPU_FLEET_SCALE_HIGH_MS", default=50.0,
    doc="Autoscaler high watermark: mean healthy-replica worst-model "
        "est_wait_ms above this for up_after consecutive ticks triggers "
        "a scale-up (warm AOT bring-up)")
ENV_FLEET_SCALE_LOW_MS = register_env(
    "MXTPU_FLEET_SCALE_LOW_MS", default=5.0,
    doc="Autoscaler low watermark: the signal below this for down_after "
        "consecutive ticks triggers a fenced scale-down (fence -> drain "
        "-> stop, never below the capacity floor)")
ENV_FLEET_SCALE_COOLDOWN_S = register_env(
    "MXTPU_FLEET_SCALE_COOLDOWN_S", default=10.0,
    doc="Seconds after any autoscaler action during which no further "
        "action fires (new capacity must be judged by the new signal, "
        "not the spike that caused it)")
ENV_FLEET_MIN_REPLICAS = register_env(
    "MXTPU_FLEET_MIN_REPLICAS", default=1,
    doc="Autoscaler floor: scale-down never goes below this many live "
        "replicas (the fence's N-1 routable floor applies on top)")
ENV_FLEET_MAX_REPLICAS = register_env(
    "MXTPU_FLEET_MAX_REPLICAS", default=4,
    doc="Autoscaler ceiling: scale-up never grows the fleet past this "
        "many live replicas")

#: replica states that no longer count toward live capacity
_DEAD_STATES = ("failed", "scaled_down", "drained", "exited")


class Autoscaler(object):
    """Closes the load -> capacity loop over one
    :class:`~.controller.ReplicaController` + the PROBER-side
    :class:`~.router.FleetRouter` (controller mode — the one that owns
    fencing; in the sharded front end that is the publisher's router,
    never a worker).  ``publisher``, when given, gets a
    ``publish_once()`` after every fence/unfence so front-end workers
    see the change within one view refresh instead of one publish
    period."""

    def __init__(self, controller, router, publisher=None,
                 min_replicas=None, max_replicas=None, high_ms=None,
                 low_ms=None, up_after=2, down_after=6, cooldown_s=None,
                 period_s=1.0, settle_s=0.5, drain_wait_s=10.0,
                 signal_fn=None, log=None):
        self.controller = controller
        self.router = router
        self.publisher = publisher
        self.min_replicas = int(get_env(ENV_FLEET_MIN_REPLICAS)
                                if min_replicas is None else min_replicas)
        self.max_replicas = int(get_env(ENV_FLEET_MAX_REPLICAS)
                                if max_replicas is None else max_replicas)
        self.high_ms = float(get_env(ENV_FLEET_SCALE_HIGH_MS)
                             if high_ms is None else high_ms)
        self.low_ms = float(get_env(ENV_FLEET_SCALE_LOW_MS)
                            if low_ms is None else low_ms)
        if self.low_ms > self.high_ms:
            raise MXNetError(
                "autoscaler watermarks inverted: low %.1fms > high "
                "%.1fms — the hysteresis band must be non-empty"
                % (self.low_ms, self.high_ms))
        if self.min_replicas < 1:
            raise MXNetError("min_replicas must be >= 1 (a fleet that "
                             "scales to zero cannot serve)")
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.cooldown_s = float(get_env(ENV_FLEET_SCALE_COOLDOWN_S)
                                if cooldown_s is None else cooldown_s)
        self.period_s = float(period_s)
        self.settle_s = float(settle_s)
        self.drain_wait_s = float(drain_wait_s)
        self.signal_fn = signal_fn
        self._log = log or (lambda msg: None)
        # guards counters and the hysteresis streaks: tick() runs on
        # the autoscale thread, but tests and operators call it (and
        # stats()) from the main thread too
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._high_streak = 0
        self._low_streak = 0
        self._last_action_at = None
        self._last_signal = None
        self.counters = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                         "blocked_floor": 0, "blocked_max": 0,
                         "blocked_min": 0, "blocked_cooldown": 0,
                         "errors": 0}

    # -- signal ------------------------------------------------------------
    def _pressure_ms(self):
        """Mean over healthy replicas of each one's worst per-model
        ``est_wait_ms``.  Mean, not max: one replica's spike is the
        SPILL policy's problem (move the traffic); the autoscaler acts
        when the fleet as a whole is behind.  Delegates to
        ``FleetRouter.pressure_ms`` — the SAME aggregation the brownout
        admission gate sheds on, so adding capacity and shedding load
        react to one number instead of fighting each other."""
        return self.router.pressure_ms()

    def _live(self):
        """Replicas that count toward capacity bounds: everything the
        controller has not written off — including ones still warming
        up, so a scale-up in flight blocks the next one."""
        return [r for r in self.controller.replicas
                if r.state not in _DEAD_STATES]

    def _publish(self):
        if self.publisher is not None:
            try:
                self.publisher.publish_once()
            except Exception:  # noqa: BLE001 — the loop publishes next
                pass

    # -- policy ------------------------------------------------------------
    def tick(self):
        """One synchronous policy evaluation (the loop body; also the
        test surface).  Returns the action taken: ``"up"``, ``"down"``
        or ``None``."""
        sig = self.signal_fn() if self.signal_fn is not None \
            else self._pressure_ms()
        self._last_signal = sig
        with self._lock:
            self.counters["ticks"] += 1
            if sig >= self.high_ms:
                self._high_streak += 1
                self._low_streak = 0
            elif sig <= self.low_ms:
                self._low_streak += 1
                self._high_streak = 0
            else:
                # the hysteresis band: no pressure either way
                self._high_streak = 0
                self._low_streak = 0
            want_up = self._high_streak >= self.up_after
            want_down = self._low_streak >= self.down_after
        if not (want_up or want_down):
            return None
        now = time.monotonic()
        if self._last_action_at is not None and \
                now - self._last_action_at < self.cooldown_s:
            with self._lock:
                self.counters["blocked_cooldown"] += 1
            return None
        if want_up:
            if len(self._live()) >= self.max_replicas:
                with self._lock:
                    self.counters["blocked_max"] += 1
                return None
            return self._scale_up(sig)
        if len(self._live()) <= self.min_replicas:
            with self._lock:
                self.counters["blocked_min"] += 1
            return None
        return self._scale_down(sig)

    def _scale_up(self, sig):
        try:
            rep = self.controller.add_replica()
        except MXNetError as e:     # draining — the fleet is going away
            with self._lock:
                self.counters["errors"] += 1
            self._log("autoscale: scale-up refused (%s)" % (e,))
            return None
        with self._lock:
            self.counters["scale_ups"] += 1
            self._high_streak = 0
        self._last_action_at = time.monotonic()
        self._log("autoscale: UP -> replica %d (signal %.1fms >= "
                  "%.1fms)" % (rep.id, sig, self.high_ms))
        return "up"

    def _scale_down(self, sig):
        """The fenced retirement dance.  Any failure unwinds the fence
        — a half-retired replica must keep serving."""
        healthy = self.router.healthy()
        if not healthy:
            return None
        rid = max(healthy)
        try:
            self.router.fence(rid)
        except MXNetError:
            # fencing would leave no routable replica — the N-1 floor
            # outranks the low watermark, always
            with self._lock:
                self.counters["blocked_floor"] += 1
            return None
        try:
            self._publish()         # workers stop routing to rid
            if self.settle_s > 0:
                time.sleep(self.settle_s)
            self._wait_drained(rid)
            self.controller.stop_replica(rid)
        except Exception as e:  # noqa: BLE001 — unwind, keep serving
            with self._lock:
                self.counters["errors"] += 1
            self._log("autoscale: scale-down of %d failed (%s: %s) — "
                      "unfenced" % (rid, type(e).__name__, e))
            self.router.unfence(rid)
            self._publish()
            return None
        self.router.unfence(rid)    # the id is gone; don't leak a fence
        self._publish()
        with self._lock:
            self.counters["scale_downs"] += 1
            self._low_streak = 0
        self._last_action_at = time.monotonic()
        self._log("autoscale: DOWN -> replica %d retired (signal "
                  "%.1fms <= %.1fms)" % (rid, sig, self.low_ms))
        return "down"

    def _wait_drained(self, rid):
        """Wait for the fenced replica's reported queue to empty (new
        work stopped at the fence; what's left is in-flight).  Bounded:
        SIGTERM itself drains accepted work to 200s, so timing out here
        costs nothing but politeness."""
        deadline = time.monotonic() + self.drain_wait_s
        while time.monotonic() < deadline:
            with self.router._lock:
                view = self.router._views.get(rid)
                stats = (view.stats or {}) if view is not None else {}
                inflight = view.inflight if view is not None else 0
            depth = sum((stats.get("queue_depth") or {}).values())
            if depth == 0 and inflight == 0:
                return
            time.sleep(0.1)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="mxfleet-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                with self._lock:
                    self.counters["errors"] += 1

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return self

    def stats(self):
        with self._lock:
            out = dict(self.counters)
            out.update({"high_streak": self._high_streak,
                        "low_streak": self._low_streak})
        out.update({"live": len(self._live()),
                    "min": self.min_replicas, "max": self.max_replicas,
                    "high_ms": self.high_ms, "low_ms": self.low_ms,
                    "last_signal_ms": self._last_signal})
        return out
