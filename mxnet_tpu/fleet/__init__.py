"""mxfleet: a multi-replica serving fleet on the mxserve stack
(docs/how_to/fleet.md).

mxserve (``mxnet_tpu/serving/``) is ONE daemon: one process, one warm
``ModelPool``, one public port — its QPS ceiling is one Python
dispatcher and one device client.  This package composes N of those
daemons into one serving system:

- :mod:`.manifest` — the fleet manifest (models, replica count, device
  placement) + each model's stable HOME replica.
- :mod:`.controller` — replica lifecycle: spawns N real
  ``tools/serve.py`` processes, each pinned to its own device subset
  (``JAX_PLATFORMS``/visible-chip env, CPU-core affinity on the CPU
  tier), supervised by the ``tools/supervise.py`` exit-code discipline
  (85/87 relaunch with ``MXTPU_RESUME=1``; any other death respawns
  within a streak budget; drains relaunch nothing).
- :mod:`.router` — the routing front end that owns the public port:
  route-by-model to the home replica, SPILL to the least-loaded
  replica when the home's queue/SLO signal crosses the bar (the
  ``/stats`` surface PR 6 built is the routing input), heartbeat-age
  eviction off ``/healthz``, exactly-once keyed retry on a dead
  replica (one resend to a different healthy replica, same request
  id — replica dedup + bucket bit-stability make it safe),
  SIGTERM drain that fences new work then drains every replica, and
  fleet-level p50/p99/shed aggregation on ``/stats``.
- :mod:`.warm` — the AOT warm store: pre-compile every (model, bucket)
  forward into ``MXTPU_COMPILE_CACHE`` so a fresh or respawned replica
  warms from disk instead of from XLA (``fleet_warm_start_x`` in
  ``bench.py fleet`` measures the win; >= 3x is the bar).
- :mod:`.view` — the shared fleet view that shards the front end: ONE
  controller-side prober publishes manifest + health + the fenced set
  into an atomic JSON snapshot with a generation counter; N
  ``FleetRouter`` worker processes (:class:`~.view.RouterWorkerSet`)
  accept on the SAME public port via SO_REUSEPORT and route off the
  snapshot — workers never probe and never coordinate.
- :mod:`.autoscale` — the loop that ACTS on the aggregated
  ``est_wait_ms`` signal: hysteresis + cooldown, scale-up through
  :meth:`~.controller.ReplicaController.add_replica` (warm AOT
  bring-up), scale-down through the mxswap fence -> drain -> stop
  path (never below the capacity floor).

``tools/fleet.py`` is the CLI (``serve`` + ``warmup`` +
``router-worker`` subcommands); ``bench.py fleet`` / ``bench.py
overdrive`` are the load generators and self-proof.  Every
``MXTPU_FLEET_*`` knob is registered EAGERLY at its owner module
below (the PR-7 lazy-registration lesson); this package never imports
jax — the router and controller are pure-host processes by design.
"""
from .manifest import (FleetManifest, parse_shape_specs,
                       replica_device_env, default_serve_py,
                       ENV_FLEET_REPLICAS)
from .controller import Replica, ReplicaController
from .router import (FleetRouter, NoHealthyReplica, ReplicaDead,
                     ENV_FLEET_SPILL_QUEUE, ENV_FLEET_HEARTBEAT_S,
                     ENV_FLEET_EVICT_S)
from .warm import build_warm_store, warm_store_manifest
from .deploy import RollingSwap
from .view import (FleetViewPublisher, FleetViewReader, RouterWorkerSet,
                   reserve_port, ENV_FLEET_WORKERS,
                   ENV_FLEET_VIEW_REFRESH_S)
from .autoscale import (Autoscaler, ENV_FLEET_SCALE_HIGH_MS,
                        ENV_FLEET_SCALE_LOW_MS,
                        ENV_FLEET_SCALE_COOLDOWN_S,
                        ENV_FLEET_MIN_REPLICAS, ENV_FLEET_MAX_REPLICAS)

__all__ = ["FleetManifest", "parse_shape_specs", "replica_device_env",
           "default_serve_py", "Replica", "ReplicaController",
           "FleetRouter", "NoHealthyReplica", "ReplicaDead",
           "build_warm_store", "warm_store_manifest", "RollingSwap",
           "FleetViewPublisher", "FleetViewReader", "RouterWorkerSet",
           "reserve_port", "Autoscaler",
           "ENV_FLEET_REPLICAS", "ENV_FLEET_SPILL_QUEUE",
           "ENV_FLEET_HEARTBEAT_S", "ENV_FLEET_EVICT_S",
           "ENV_FLEET_WORKERS", "ENV_FLEET_VIEW_REFRESH_S",
           "ENV_FLEET_SCALE_HIGH_MS", "ENV_FLEET_SCALE_LOW_MS",
           "ENV_FLEET_SCALE_COOLDOWN_S", "ENV_FLEET_MIN_REPLICAS",
           "ENV_FLEET_MAX_REPLICAS"]
