"""Routing front end: ONE public HTTP port in front of N replica
daemons.

The Clipper split, scaled out: the router owns admission and placement,
the replicas own weights and batching.  Policy per request:

- **route by model**: each model's HOME replica (a stable function of
  the manifest — ``FleetManifest.home``) takes its traffic by default,
  concentrating a model's buckets where they stay hot.
- **spill**: when the home's reported queue depth for the model (the
  ``/stats`` surface mxserve already exposes, plus the router's own
  in-flight count toward that replica) reaches
  ``MXTPU_FLEET_SPILL_QUEUE``, or its estimated wait crosses the SLO
  bar, the request goes to the least-loaded healthy replica instead —
  every replica holds the whole warm pool, so spilling needs no model
  load.
- **health**: a poll thread GETs ``/healthz`` + ``/stats`` from every
  replica each ``MXTPU_FLEET_HEARTBEAT_S``; a replica whose last
  successful heartbeat is older than ``MXTPU_FLEET_EVICT_S`` is EVICTED
  from routing until it answers again (a respawned replica rejoins the
  moment its new port file appears and a probe succeeds).

EXACTLY-ONCE STANCE (supersedes the PR 11 fail-once rule): a predict
in flight to a replica that dies is resent ONCE to a different healthy
replica with the SAME idempotency key (``X-MXTPU-Request-Id``) — safe
because (a) each replica's dedup cache collapses a duplicate onto the
original execution, and (b) even on a dedup miss the batcher's
bit-exactness contract makes re-execution of the same bytes
bit-identical (serving/batcher.py).  A retried success carries
``"retried": true``; only when NO other healthy replica exists (or the
resend also dies) does the client see a 502.  Tail defense rides the
same key: a request older than an adaptive latency percentile is
HEDGED to the next-least-loaded replica (MXTPU_FLEET_HEDGE_PCT), first
answer wins, and under brownout (aggregate est_wait past
MXTPU_FLEET_BROWNOUT_MS) the router sheds low-priority/over-quota
work with Retry-After 429s before queues build.  ``POST /swap`` keeps
the never-retried stance — a swap is not keyed and genuinely not
idempotent (fleet/deploy.py).

Shutdown: SIGTERM fences new work (503 on the public port), waits for
the router's in-flight forwards, then forwards the drain to every
replica through the controller (each drains to rc 0 — the mxserve
contract), then stops.  ``/stats`` aggregates the per-replica counters
plus the router-measured fleet-level p50/p99.
"""
from __future__ import annotations

import glob
import json
import os
import queue
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..base import MXNetError, get_env, register_env
from ..resilience import faults
from ..serving.frontend import Stats
from .view import FleetViewReader, OutlierDetector, worker_stats_path

__all__ = ["FleetRouter", "NoHealthyReplica", "ReplicaDead",
           "ENV_FLEET_SPILL_QUEUE", "ENV_FLEET_HEARTBEAT_S",
           "ENV_FLEET_EVICT_S", "ENV_FLEET_HEDGE_PCT",
           "ENV_FLEET_HEDGE_MIN_MS", "ENV_FLEET_BROWNOUT_MS"]

ENV_FLEET_SPILL_QUEUE = register_env(
    "MXTPU_FLEET_SPILL_QUEUE", default=8,
    doc="Queue depth (replica-reported + router in-flight) at a model's "
        "home replica beyond which the router spills the request to the "
        "least-loaded healthy replica")
ENV_FLEET_HEARTBEAT_S = register_env(
    "MXTPU_FLEET_HEARTBEAT_S", default=1.0,
    doc="Router health-poll period: every replica's /healthz + /stats "
        "are probed this often (also the staleness bound on the routing "
        "signal)")
ENV_FLEET_EVICT_S = register_env(
    "MXTPU_FLEET_EVICT_S", default=5.0,
    doc="Heartbeat age beyond which a replica is evicted from routing "
        "(it rejoins on the next successful probe — e.g. after the "
        "controller respawned it warm from the AOT store)")
ENV_FLEET_HEDGE_PCT = register_env(
    "MXTPU_FLEET_HEDGE_PCT", default=0.0,
    doc="Hedged requests: a forward older than this percentile of "
        "recent router-observed latency gets a backup sent to the "
        "next-least-loaded replica with the same idempotency key, "
        "first answer wins (losers count `hedge_wasted`); 0 disables "
        "hedging (it is also gated off with <2 routable replicas or "
        "in brownout)")
ENV_FLEET_HEDGE_MIN_MS = register_env(
    "MXTPU_FLEET_HEDGE_MIN_MS", default=25.0,
    doc="Floor on the adaptive hedge trigger: never hedge a request "
        "younger than this many ms, whatever the latency percentile "
        "says (bounds duplicate-execution cost at low latency)")
ENV_FLEET_BROWNOUT_MS = register_env(
    "MXTPU_FLEET_BROWNOUT_MS", default=0.0,
    doc="Brownout admission control: when the fleet's aggregate "
        "est_wait_ms (the autoscaler's pressure signal) exceeds this, "
        "router workers shed priority<=0 and over-quota-tenant work "
        "with Retry-After 429s BEFORE queues build; 0 disables")

#: fault point: after a delivered forward, the router re-sends the
#: SAME request (same body, same idempotency key) once more — the
#: deterministic duplicate that proves the replica-side dedup cache
#: collapses it instead of double-executing
DUP_REQUEST_FAULT = "dup_request"


class NoHealthyReplica(MXNetError):
    """No routable replica for the request (HTTP 503)."""


class ReplicaDead(MXNetError):
    """The forward to the chosen replica failed at the transport level
    — the caller applies the exactly-once stance (one keyed resend to
    a different healthy replica; HTTP 502 only when that is
    impossible)."""


class _ReplicaView(object):
    """The router's picture of one replica (updated by the health loop
    + forwarding outcomes)."""

    __slots__ = ("id", "addr", "last_ok", "stats", "inflight", "probes",
                 "probe_retries", "errors")

    def __init__(self, rid):
        self.id = rid
        self.addr = None            # (host, port) once known
        self.last_ok = None         # monotonic of last good /healthz
        self.stats = None           # last /stats payload
        self.inflight = 0           # router-side forwards in flight
        self.probes = 0
        self.probe_retries = 0      # jittered second tries (GETs only)
        self.errors = 0


class FleetRouter(object):
    """``endpoints``: a :class:`~.controller.ReplicaController` (live
    port discovery + drain forwarding), a static ``{id: (host, port)}``
    dict (tests, external replicas), or a
    :class:`~.view.FleetViewReader` — **view mode**, the sharded front
    end's worker: health, addresses, per-replica stats and the fenced
    set all come from the published snapshot, this process never probes
    and never fences.  ``reuse_port`` binds the public port with
    SO_REUSEPORT so N workers share it; ``worker_id`` + ``run_dir``
    turn on the periodic counter dump that lets ANY worker answer
    ``/stats`` for the whole shard (sibling dumps merged with live
    counters)."""

    def __init__(self, endpoints, manifest, host="127.0.0.1", port=0,
                 spill_queue=None, heartbeat_s=None, evict_s=None,
                 slo_ms=0.0, request_timeout=60.0, reuse_port=False,
                 worker_id=None, run_dir=None):
        self.manifest = manifest
        self.host, self.port = host, int(port)
        self.reuse_port = bool(reuse_port)
        self.worker_id = worker_id
        self.run_dir = run_dir
        self.spill_queue = int(get_env(ENV_FLEET_SPILL_QUEUE)
                               if spill_queue is None else spill_queue)
        self.heartbeat_s = float(get_env(ENV_FLEET_HEARTBEAT_S)
                                 if heartbeat_s is None else heartbeat_s)
        self.evict_s = float(get_env(ENV_FLEET_EVICT_S)
                             if evict_s is None else evict_s)
        self.slo_ms = float(slo_ms or 0.0)
        self.request_timeout = float(request_timeout)
        self.hedge_pct = float(get_env(ENV_FLEET_HEDGE_PCT))
        self.hedge_min_ms = float(get_env(ENV_FLEET_HEDGE_MIN_MS))
        self.brownout_ms = float(get_env(ENV_FLEET_BROWNOUT_MS))
        #: gray-failure ejection (controller/static mode only: a view
        #: worker inherits ejection through the published healthy bit)
        self.outliers = OutlierDetector(
            hold_s=max(2.0 * self.heartbeat_s, 1.0))
        self.stats = Stats()
        self.draining = False
        self._controller = None
        self._static = None
        self._view = None
        self._views = {}
        if isinstance(endpoints, FleetViewReader):
            self._view = endpoints      # worker: snapshot-fed, no probe
        elif hasattr(endpoints, "ports"):
            self._controller = endpoints
            if len(endpoints.replicas) < 1:
                raise MXNetError("a fleet needs at least one replica")
            for rid in range(len(endpoints.replicas)):
                self._views[rid] = _ReplicaView(rid)
        else:
            self._static = {rid: tuple(addr)
                            for rid, addr in dict(endpoints).items()}
            if len(self._static) < 1:
                raise MXNetError("a fleet needs at least one replica")
            for rid in self._static:
                self._views[rid] = _ReplicaView(rid)
        self._order = sorted(self._views)
        #: replicas held out of routing by a rolling swap
        #: (fleet/deploy.py): fenced != evicted — the replica is
        #: healthy and still finishing its in-flight work, it just
        #: takes no NEW work while its weights swap
        self._fenced = set()
        #: the active RollingSwap, when one is attached (fleet serve
        #: --watch) — surfaced on /stats as rollout progress
        self.deploy = None
        self._lock = threading.Lock()
        self._local = threading.local()
        self._server = None
        self._stopped = threading.Event()
        self._stop_health = threading.Event()
        self._health_thread = None
        self._stop_dump = threading.Event()
        self._dump_thread = None
        #: serve/drain handshake: a drain that arrives BEFORE the
        #: accept loop starts marks _aborted so serve_forever returns
        #: immediately instead of serving a drained fleet forever;
        #: once _serving, the drain uses server.shutdown().  The lock
        #: makes the two transitions atomic — without it a drain could
        #: check "not serving yet" in the same instant the accept loop
        #: starts, and neither side would stop the server.
        self._life_lock = threading.Lock()
        self._serving = False
        self._aborted = False
        self.replica_rcs = None     # {id: rc} after a drain

    # -- replica discovery + health ---------------------------------------
    def _addresses(self):
        if self._static is not None:
            return dict(self._static)
        if self._view is not None:
            self._sync_view()
            with self._lock:
                return {rid: v.addr for rid, v in self._views.items()}
        addrs = {rid: ("127.0.0.1", port) if port is not None else None
                 for rid, port in self._controller.ports().items()}
        # the replica SET is dynamic under autoscaling: adopt new
        # replicas, drop scaled-down ones (their fences go with them)
        with self._lock:
            for rid in addrs:
                if rid not in self._views:
                    self._views[rid] = _ReplicaView(rid)
            for rid in [r for r in self._views if r not in addrs]:
                del self._views[rid]
                self._fenced.discard(rid)
            self._order = sorted(self._views)
        return addrs

    def _sync_view(self):
        """View mode: refresh the routing state from the published
        snapshot (addresses, per-replica stats, health, the fenced
        set).  A replica the snapshot calls healthy is routable NOW —
        even off a stale snapshot (publisher hiccup): routing to a
        last-known-healthy replica is safe, because a death since the
        snapshot surfaces as a transport failure the exactly-once
        stance absorbs (one keyed resend elsewhere).  Worker-local
        inflight/error counters survive the sync."""
        doc = self._view.doc()
        now = time.monotonic()
        with self._lock:
            seen = set()
            for key, ent in (doc.get("replicas") or {}).items():
                rid = ent.get("id", key)
                seen.add(rid)
                view = self._views.get(rid)
                if view is None:
                    view = self._views[rid] = _ReplicaView(rid)
                addr = ent.get("addr")
                view.addr = tuple(addr) if addr else None
                view.stats = ent.get("stats")
                view.last_ok = now if ent.get("healthy") else None
            for rid in [r for r in self._views if r not in seen]:
                del self._views[rid]
            self._fenced = set(doc.get("fenced") or [])
            self._order = sorted(self._views)

    def _probe_one(self, view, addr):
        """One /healthz (+ /stats) round trip; returns ``"ok"``,
        ``"draining"`` (the replica deliberately fenced itself) or
        ``"down"`` (transport-level miss)."""
        import http.client
        conn = http.client.HTTPConnection(
            addr[0], addr[1], timeout=max(0.2, min(self.heartbeat_s,
                                                   2.0)))
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return "down"
            payload = json.loads(body.decode("utf-8"))
            if payload.get("status") == "draining":
                # a draining replica takes no work — evict it NOW, not
                # after the heartbeat age runs out (a rolling restart
                # would otherwise bounce 503s off it for evict_s)
                with self._lock:
                    view.last_ok = None
                return "draining"
            conn.request("GET", "/stats")
            resp = conn.getresponse()
            sbody = resp.read()
            stats = json.loads(sbody.decode("utf-8")) \
                if resp.status == 200 else None
        except Exception:  # noqa: BLE001 — any transport failure = miss
            return "down"
        finally:
            conn.close()
        with self._lock:
            view.addr = addr
            view.last_ok = time.monotonic()
            if stats is not None:
                view.stats = stats
        return "ok"

    #: upper bound on the jittered pause before a probe's single retry
    PROBE_RETRY_JITTER_S = 0.08

    def probe(self):
        """One full probe pass (the health loop's body; also called
        synchronously at start so the first routed request never races
        the first heartbeat).

        A transport-level miss gets ONE retry after a jittered pause
        before the heartbeat-age clock is allowed to advance toward
        eviction: a single dropped packet on a loaded replica must not
        start the eviction countdown.  (Predict forwards have their own
        keyed retry discipline in ``proxy_predict`` — these probe GETs
        retry freely because they are idempotent by nature.)  A replica
        that reported ``draining`` is a deliberate eviction, not a
        miss: no retry.

        Retries run CONCURRENTLY with one bounded join: a few
        black-holed hosts (each costing a full connect timeout) must
        not stretch the pass past ``evict_s`` and age out the healthy
        replicas that were stamped at the start of it."""
        import random
        if self._view is not None:
            # workers NEVER probe — that is the whole point of the
            # shared view (one prober, N consumers)
            return self.healthy()
        addrs = self._addresses()
        misses = []
        for rid, view in list(self._views.items()):
            with self._lock:
                view.probes += 1
            addr = addrs.get(rid)
            if addr is None:
                continue            # no port file yet (spawning)
            if self._probe_one(view, addr) == "down":
                misses.append((view, addr))
        if misses:
            def _retry(view, addr):
                time.sleep(random.uniform(
                    0.0, min(self.PROBE_RETRY_JITTER_S,
                             self.heartbeat_s / 4.0)))
                with self._lock:
                    view.probe_retries += 1
                self._probe_one(view, addr)

            threads = [threading.Thread(target=_retry, args=m,
                                        name="mxfleet-probe-retry",
                                        daemon=True)
                       for m in misses]
            for t in threads:
                t.start()
            deadline = time.monotonic() + min(self.heartbeat_s, 2.0) \
                + self.PROBE_RETRY_JITTER_S
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._update_outliers()
        return self.healthy()

    def _update_outliers(self):
        """Feed the gray-failure detector one pass (controller/static
        mode; the probe loop's tail): recent-p99 per replica from its
        own /stats, cumulative forward errors, and the pre-ejection
        routable set so the detector can hold its max-eject/N-1
        floor."""
        det = self.outliers
        if not det.enabled or self._view is not None:
            return
        now = time.monotonic()
        with self._lock:
            routable = [rid for rid in self._order
                        if rid not in self._fenced
                        and self._views[rid].last_ok is not None
                        and now - self._views[rid].last_ok <= self.evict_s
                        and self._views[rid].addr is not None]
            lat, errs = {}, {}
            for rid in routable:
                view = self._views[rid]
                lm = ((view.stats or {}).get("latency_ms") or {})
                sample = lm.get("p99_recent", lm.get("p99"))
                if sample is not None:
                    lat[rid] = float(sample)
                errs[rid] = view.errors
        for key, n in det.update(routable, lat, errs, now=now).items():
            if n:
                self.stats.inc(key, n)

    def _health_loop(self):
        while not self._stop_health.wait(self.heartbeat_s):
            try:
                self.probe()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    def healthy(self):
        """Routable replica ids: probed OK within the eviction window,
        not fenced by a rolling swap, and not held out by gray-failure
        ejection (view mode: as the published snapshot says — the sync
        stamps healthy replicas fresh, so a stale snapshot keeps its
        last-known-healthy set routable; the snapshot's healthy bit
        already folds controller-side ejection)."""
        if self._view is not None:
            self._sync_view()
            ejected = set()
        else:
            ejected = self.outliers.ejected()
        now = time.monotonic()
        with self._lock:
            return [rid for rid in self._order
                    if rid not in self._fenced
                    and rid not in ejected
                    and self._views[rid].last_ok is not None
                    and now - self._views[rid].last_ok <= self.evict_s
                    and self._views[rid].addr is not None]

    # -- rolling-swap fencing (fleet/deploy.py) ----------------------------
    def fence(self, rid):
        """Hold ``rid`` out of routing (new traffic goes elsewhere;
        its in-flight work finishes normally).  Raises when fencing it
        would leave NO routable replica — a rollout must never take
        the last server away (capacity floor N-1)."""
        if self._view is not None:
            raise MXNetError(
                "fencing is the controller's job in sharded mode — "
                "fence via the publisher-side router, the snapshot "
                "carries it to every worker")
        now = time.monotonic()
        ejected = self.outliers.ejected()
        with self._lock:
            others = [r for r in self._order
                      if r != rid and r not in self._fenced
                      and r not in ejected
                      and self._views[r].last_ok is not None
                      and now - self._views[r].last_ok <= self.evict_s
                      and self._views[r].addr is not None]
            if not others:
                raise MXNetError(
                    "fencing replica %s would leave no routable "
                    "replica — rollout must wait (a 1-replica fleet "
                    "swaps in place: the swap itself is drop-free)"
                    % (rid,))
            self._fenced.add(rid)
        return self

    def unfence(self, rid):
        """Rejoin ``rid`` to routing (the swap finished or failed —
        either way the replica serves a consistent epoch)."""
        with self._lock:
            self._fenced.discard(rid)
        return self

    def fenced(self):
        with self._lock:
            return sorted(self._fenced)

    def view_export(self):
        """Per-replica routing state for the shared fleet view
        (fleet/view.py publishes it; router workers consume it).  The
        ``healthy`` flag already folds in fencing — a worker needs one
        bit, not the derivation."""
        healthy = set(self.healthy())
        eject = self.outliers.export()
        ctrl = {r["id"]: r for r in self._controller.snapshot()} \
            if self._controller is not None else {}
        out = {}
        with self._lock:
            for rid in self._order:
                view = self._views[rid]
                sup = ctrl.get(rid, {})
                out[str(rid)] = {
                    "id": rid,
                    "addr": list(view.addr) if view.addr else None,
                    # the healthy bit folds fencing AND ejection — a
                    # worker needs one bit; the eject detail rides
                    # alongside for observability
                    "healthy": rid in healthy,
                    "ejected": bool(
                        (eject.get(rid) or {}).get("ejected")),
                    "stats": view.stats,
                    "forward_errors": view.errors,
                    "state": sup.get("state"),
                    # supervision fields travel with the view: in the
                    # sharded front end the controller lives in the
                    # parent, but any worker must still answer the full
                    # /stats table (pid drives kill-replica drills,
                    # restarts drives respawn crediting)
                    "pid": sup.get("pid"),
                    "restarts": sup.get("restarts"),
                    "last_rc": sup.get("last_rc")}
        return out

    # -- routing policy ----------------------------------------------------
    def _load(self, view, model=None):
        """Routing load signal: replica-reported queue depth (per model
        when asked, total otherwise) + the router's own in-flight count
        toward it (the fast-moving half of the signal)."""
        depth = 0
        if view.stats:
            depths = view.stats.get("queue_depth") or {}
            depth = depths.get(model, 0) if model is not None \
                else sum(depths.values())
        return depth + view.inflight

    def route(self, model):
        """Pick the replica for one request; raises
        :class:`NoHealthyReplica` when nothing is routable.  Returns
        ``(replica_id, reason)`` with ``reason`` one of ``None`` (the
        healthy home took it), ``"spilled"`` (the home was healthy but
        past its depth/SLO bar — the LOAD policy moved it) or
        ``"rerouted"`` (the home was not routable — failover, counted
        separately so the spill counter stays evidence of load spill,
        not of dead homes)."""
        if model not in self.manifest.models:
            raise MXNetError("no model %r in the fleet manifest "
                             "(have: %s)" % (model, self.manifest.names()))
        candidates = self.healthy()
        if not candidates:
            raise NoHealthyReplica(
                "no healthy replica for %r (fleet of %d, all evicted "
                "or starting)" % (model, len(self._views)))
        if self._view is not None:
            age = self._view.age_s()
            if age is not None and age > self.evict_s:
                # routing on a stale snapshot is SAFE (the keyed
                # resend covers any death since) but worth counting: a
                # climbing stale_view_routes means the publisher is
                # gone
                self.stats.inc("stale_view_routes")
        home = self._order[self.manifest.home(model) % len(self._order)]
        with self._lock:
            if home in candidates:
                hview = self._views[home]
                depth = self._load(hview, model)
                est = ((hview.stats or {}).get("est_wait_ms") or {}) \
                    .get(model, 0.0)
                if depth < self.spill_queue and \
                        (self.slo_ms <= 0 or est <= self.slo_ms):
                    return home, None
            # spill/reroute: least-loaded healthy replica, ties broken
            # AWAY from the home — a home past its bar sheds overflow
            # when loads tie (that is what the bar means), but a
            # deeper-loaded alternative never wins just for not being
            # the home (spill balances load, it must not invert it)
            best = min(candidates,
                       key=lambda rid: (self._load(self._views[rid]),
                                        rid == home, rid))
        if best == home:
            return best, None
        return best, "spilled" if home in candidates else "rerouted"

    # -- forwarding --------------------------------------------------------
    #: retire a pooled keep-alive connection idle longer than this:
    #: the replica handler's socket timeout closes ITS side after 10s
    #: (serving/frontend.py), and a request written onto such a socket
    #: fails at getresponse() — which this router must treat as a dead
    #: replica (one keyed resend elsewhere, then 502).  Refreshing
    #: before the replica's deadline keeps idle gaps from minting
    #: spurious retries.
    CONN_IDLE_S = 5.0

    def _connection(self, rid, addr, fresh=False):
        """Per-(handler-)thread keep-alive connection to a replica."""
        import http.client
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        key = (rid, addr)
        now = time.monotonic()
        entry = pool.get(key)
        if entry is not None and not fresh and \
                now - entry[1] <= self.CONN_IDLE_S:
            conn = entry[0]
        else:
            if entry is not None:
                entry[0].close()
            conn = http.client.HTTPConnection(
                addr[0], addr[1], timeout=self.request_timeout)
        pool[key] = (conn, now)
        return conn

    def forward(self, rid, method, path, body=None, headers=None):
        """One proxied request -> ``(status, raw_body, content_type)``.
        A transport failure raises :class:`ReplicaDead`; THIS method
        never resends — the exactly-once retry decision (same key,
        different replica, once) belongs to :meth:`proxy_predict`."""
        with self._lock:
            addr = self._views[rid].addr
        if addr is None:
            raise ReplicaDead("replica %d has no known address" % rid)
        try:
            conn = self._connection(rid, addr)
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
            except Exception:
                # the keep-alive socket may have idled out between
                # requests; ONE fresh connection for the SEND phase only
                # (nothing reached the replica yet — not a resend)
                conn = self._connection(rid, addr, fresh=True)
                conn.request(method, path, body=body,
                             headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            ctype = resp.getheader("Content-Type") or "application/json"
            return resp.status, data, ctype
        except Exception as e:  # noqa: BLE001 — transport-level loss
            pool = getattr(self._local, "conns", None)
            dead = pool.pop((rid, addr), None) if pool else None
            if dead is not None:
                try:
                    dead[0].close()
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            with self._lock:
                self._views[rid].errors += 1
            raise ReplicaDead(
                "replica %d died mid-request (%s: %s)"
                % (rid, type(e).__name__, e))

    # -- load pressure (shared with fleet/autoscale.py) --------------------
    def pressure_ms(self):
        """Aggregate fleet pressure: mean over healthy replicas of each
        one's worst per-model ``est_wait_ms``.  ONE definition, two
        consumers — the autoscaler's scale signal (fleet/autoscale.py)
        and the brownout admission gate: capacity growth and load
        shedding must watch the same number or they fight each
        other."""
        healthy = self.healthy()
        if not healthy:
            return 0.0
        worst = []
        with self._lock:
            for rid in healthy:
                view = self._views.get(rid)
                est = ((view.stats or {}).get("est_wait_ms") or {}) \
                    if view is not None else {}
                worst.append(max(est.values()) if est else 0.0)
        return sum(worst) / len(worst) if worst else 0.0

    def _flooder_tenant(self):
        """The tenant holding the largest summed queued depth across
        the fleet, when that depth has reached the spill bound — the
        over-quota tenant brownout sheds even at priority > 0."""
        depths = {}
        with self._lock:
            for view in self._views.values():
                per_model = (view.stats or {}).get("tenants") or {}
                for depth_map in per_model.values():
                    for tenant, d in (depth_map or {}).items():
                        depths[tenant] = depths.get(tenant, 0) + int(d)
        if not depths:
            return None
        tenant = max(depths, key=lambda t: depths[t])
        return tenant if depths[tenant] >= self.spill_queue else None

    def _brownout_sheds(self, headers):
        """Whether THIS request goes first under brownout: everything
        not explicitly prioritized (priority <= 0), plus the flooder
        tenant's work regardless of priority."""
        headers = headers or {}
        try:
            priority = int(headers.get("X-MXTPU-Priority") or 0)
        except (TypeError, ValueError):
            priority = 0
        if priority <= 0:
            return True
        tenant = headers.get("X-MXTPU-Tenant")
        return tenant is not None and tenant == self._flooder_tenant()

    # -- exactly-once forwarding + tail defense ----------------------------
    def _pick_other(self, exclude):
        """Least-loaded healthy replica outside ``exclude`` — the
        retry/hedge target; ``None`` means neither applies (the
        single-routable-replica gate)."""
        exclude = set(exclude)
        cands = [r for r in self.healthy() if r not in exclude]
        with self._lock:
            cands = [r for r in cands if r in self._views]
            if not cands:
                return None
            return min(cands,
                       key=lambda r: (self._load(self._views[r]), r))

    def _hedge_threshold_ms(self):
        """Adaptive hedge trigger: the configured percentile of recent
        router-observed latency, floored at ``hedge_min_ms``; ``None``
        disables hedging."""
        if self.hedge_pct <= 0:
            return None
        pct = self.stats.latency_percentile(self.hedge_pct)
        return max(self.hedge_min_ms, float(pct)) \
            if pct is not None else self.hedge_min_ms

    def _mark_retried(self, data, ctype):
        """Surface ``"retried": true`` in a JSON response body — the
        client-visible receipt that the exactly-once layer resent the
        request on its behalf."""
        if "json" not in (ctype or ""):
            return data
        try:
            payload = json.loads(data.decode("utf-8"))
            payload["retried"] = True
            return json.dumps(payload).encode("utf-8")
        except Exception:  # noqa: BLE001 — any non-object body: as-is
            return data

    def _spawn_attempt(self, rid, path, body, headers, results, state):
        """One forward attempt on a helper thread (the hedged path);
        results land on ``results`` as ``(rid, (status, data, ctype)
        or None, error or None)``.  An attempt finishing after the
        request settled is the hedge race's loser: ``hedge_wasted``."""
        def run():
            with self._lock:
                view = self._views.get(rid)
                if view is not None:
                    view.inflight += 1
            try:
                try:
                    out = self.forward(rid, "POST", path, body=body,
                                       headers=headers)
                    err = None
                except ReplicaDead as e:
                    out, err = None, e
            finally:
                with self._lock:
                    view = self._views.get(rid)
                    if view is not None:
                        view.inflight -= 1
                # the pool is per-thread and this thread is about to
                # die — close the sockets now instead of leaving them
                # to the GC so attempt threads don't pile up FDs
                for conn in getattr(self._local, "conns", {}).values():
                    try:
                        conn.close()
                    except Exception:  # noqa: BLE001 — teardown only
                        pass
                self._local.conns = {}
            with state["lock"]:
                late = state["done"]
            if late:
                self.stats.inc("hedge_wasted")
            results.put((rid, out, err))
        threading.Thread(target=run, name="mxfleet-attempt",
                         daemon=True).start()

    def _forward_exactly_once(self, rid, path, body, headers):
        """Primary forward + at most ONE keyed resend to a different
        healthy replica on transport failure (the request id in
        ``headers`` makes the resend safe — replica dedup collapses a
        duplicate, and bucket bit-stability makes even a dedup-miss
        re-execution bit-identical).  Returns ``(status, data, ctype,
        final_rid, resent)``; ``status None`` = total transport failure
        with the error message in ``data``."""
        with self._lock:
            view = self._views.get(rid)
            if view is not None:
                view.inflight += 1
        try:
            try:
                status, data, ctype = self.forward(
                    rid, "POST", path, body=body, headers=headers)
                return status, data, ctype, rid, False
            except ReplicaDead as e:
                first_err = e
        finally:
            with self._lock:
                view = self._views.get(rid)
                if view is not None:
                    view.inflight -= 1
        alt = self._pick_other({rid})
        if alt is None:
            return None, ("%s — no other healthy replica to resend to"
                          % (first_err,)), None, rid, False
        self.stats.inc("retries")
        with self._lock:
            view = self._views.get(alt)
            if view is not None:
                view.inflight += 1
        try:
            try:
                status, data, ctype = self.forward(
                    alt, "POST", path, body=body, headers=headers)
                return status, data, ctype, alt, True
            except ReplicaDead as e2:
                return None, ("%s — after one keyed resend" % (e2,)), \
                    None, alt, True
        finally:
            with self._lock:
                view = self._views.get(alt)
                if view is not None:
                    view.inflight -= 1

    def _forward_hedged(self, rid, path, body, headers, thr_ms):
        """Tail-defense forward: the primary attempt runs on a helper
        thread; past ``thr_ms`` with no answer, a backup goes to the
        next-least-loaded replica with the SAME key (``hedges``) and
        the first answer wins.  A transport failure while the other
        attempt is still in flight lets that attempt double as the
        retry; with nothing in flight the explicit one-resend rule
        applies, same as the inline path."""
        results = queue.Queue()
        state = {"lock": threading.Lock(), "done": False}
        launched = [rid]
        self._spawn_attempt(rid, path, body, headers, results, state)
        outstanding = 1
        got = None
        try:
            try:
                got = results.get(timeout=thr_ms / 1000.0)
            except queue.Empty:
                backup = self._pick_other(set(launched))
                if backup is not None:
                    self.stats.inc("hedges")
                    launched.append(backup)
                    self._spawn_attempt(backup, path, body, headers,
                                        results, state)
                    outstanding += 1
            failed = 0
            retried_once = False
            last_err, last_rid = None, rid
            while outstanding > 0:
                if got is None:
                    try:
                        got = results.get(
                            timeout=self.request_timeout + 5.0)
                    except queue.Empty:
                        break
                arid, out, err = got
                got = None
                outstanding -= 1
                if err is None:
                    status, data, ctype = out
                    return status, data, ctype, arid, failed > 0
                failed += 1
                last_err, last_rid = err, arid
                if outstanding > 0:
                    continue        # the hedge doubles as the retry
                if not retried_once:
                    alt = self._pick_other(set(launched))
                    if alt is not None:
                        retried_once = True
                        self.stats.inc("retries")
                        launched.append(alt)
                        self._spawn_attempt(alt, path, body, headers,
                                            results, state)
                        outstanding += 1
            msg = str(last_err) if last_err is not None else \
                ("request timed out across %d attempt(s)"
                 % len(launched))
            return None, msg, None, last_rid, failed > 1 or retried_once
        finally:
            with state["lock"]:
                state["done"] = True

    def proxy_predict(self, model, body, headers):
        """The full per-request path: brownout gate -> route -> forward
        (exactly-once retry + optional hedge) -> account.  Returns
        ``(status, raw_body, content_type)``."""
        if self.draining:
            return 503, json.dumps(
                {"error": "fleet is draining"}).encode("utf-8"), \
                "application/json"
        in_brownout = False
        if self.brownout_ms > 0:
            pressure = self.pressure_ms()
            in_brownout = pressure > self.brownout_ms
            if in_brownout and self._brownout_sheds(headers):
                tenant = (headers or {}).get("X-MXTPU-Tenant")
                self.stats.inc("brownout_shed")
                self.stats.inc("brownout_shed:%s" % (tenant or "-",))
                retry_after = max(0.5, pressure / 1000.0)
                return 429, json.dumps(
                    {"error": "brownout: fleet pressure %.1fms past "
                     "%.1fms — shed before queueing" % (
                         pressure, self.brownout_ms),
                     "reason": "brownout", "tenant": tenant,
                     "retry_after_s": round(retry_after, 3)}
                ).encode("utf-8"), "application/json"
        try:
            rid, reason = self.route(model)
        except NoHealthyReplica as e:
            self.stats.inc("no_replica")
            return 503, json.dumps(
                {"error": str(e)}).encode("utf-8"), "application/json"
        except MXNetError as e:     # unknown model
            return 404, json.dumps(
                {"error": str(e)}).encode("utf-8"), "application/json"
        path = "/predict/%s" % model
        tic = time.monotonic()
        # hedging is gated off in brownout (a fleet already shedding
        # load must not mint duplicate work) — the retry stance is NOT:
        # absorbing a dead replica is cheap exactly when it matters
        thr_ms = None if in_brownout else self._hedge_threshold_ms()
        if thr_ms is None:
            status, data, ctype, final_rid, resent = \
                self._forward_exactly_once(rid, path, body, headers)
        else:
            status, data, ctype, final_rid, resent = \
                self._forward_hedged(rid, path, body, headers, thr_ms)
        if status is None:
            # replica_errors counts FINAL client-visible failures, so
            # the 502 ledger (chaos drills) stays exact; per-attempt
            # transport failures live in each view's forward_errors
            self.stats.inc("replica_errors")
            return 502, json.dumps(
                {"error": data, "replica": final_rid,
                 "retried": resent}).encode("utf-8"), "application/json"
        if resent:
            self.stats.inc("retry_ok")
            data = self._mark_retried(data, ctype)
        self.stats.inc("routed")
        if reason is not None:
            self.stats.inc(reason)      # "spilled" | "rerouted"
        self.stats.record_latency((time.monotonic() - tic) * 1000.0)
        if faults.consume(DUP_REQUEST_FAULT):
            # deterministic duplicate: deliver the SAME request (same
            # body, same key) once more — the replica-side dedup cache
            # must collapse it onto the original execution
            self.stats.inc("dup_requests")
            try:
                self.forward(final_rid, "POST", path, body=body,
                             headers=headers)
            except ReplicaDead:
                pass
        return status, data, ctype

    # -- observation -------------------------------------------------------
    def stats_payload(self):
        """Fleet-level aggregation: router counters + router-measured
        p50/p99 (every request crosses the router, so its window IS the
        fleet latency distribution) + summed per-replica shed/served
        counters + the per-replica table."""
        healthy = set(self.healthy())
        fleet_counters = {}
        freshness = []
        replicas = {}
        ctrl = {r["id"]: r for r in self._controller.snapshot()} \
            if self._controller is not None else {}
        if not ctrl and self._view is not None:
            # sharded front end: no controller in this process — the
            # supervision fields (state/pid/restarts/last_rc) arrive
            # through the published view instead, so a router worker's
            # /stats table matches the controller-side one
            for rid, ent in self._view.replicas().items():
                sup = {k: ent[k]
                       for k in ("state", "pid", "restarts", "last_rc")
                       if ent.get(k) is not None}
                if sup:
                    ctrl[rid] = sup
        now = time.monotonic()
        if self._view is not None:
            ejected = {rid for rid, ent in self._view.replicas().items()
                       if ent.get("ejected")}
        else:
            ejected = self.outliers.ejected()
        with self._lock:
            for rid in self._order:
                view = self._views[rid]
                entry = {"healthy": rid in healthy,
                         "fenced": rid in self._fenced,
                         "ejected": rid in ejected,
                         "port": view.addr[1] if view.addr else None,
                         "inflight": view.inflight,
                         "forward_errors": view.errors,
                         "probe_retries": view.probe_retries,
                         "heartbeat_age_s":
                             round(now - view.last_ok, 3)
                             if view.last_ok is not None else None}
                if view.stats:
                    entry["queue_depth"] = view.stats.get("queue_depth")
                    entry["est_wait_ms"] = view.stats.get("est_wait_ms")
                    # per-replica served epochs: the rollout-progress
                    # signal a rolling swap advances one replica at a
                    # time (fleet/deploy.py)
                    entry["epochs"] = view.stats.get("epochs")
                    # per-model publish->served freshness from each
                    # replica's watcher (serving/deploy.py) — the
                    # region drill aggregates the fleet-wide worst case
                    fresh = {}
                    for name, blk in (view.stats.get("deploy")
                                      or {}).items():
                        ms = (blk or {}).get("last_freshness_ms")
                        if ms is not None:
                            fresh[name] = ms
                            freshness.append(ms)
                    if fresh:
                        entry["freshness_ms"] = fresh
                    for k, v in (view.stats.get("counters")
                                 or {}).items():
                        fleet_counters[k] = fleet_counters.get(k, 0) + v
                entry.update(ctrl.get(rid, {}))
                replicas[rid] = entry
        if self._view is not None and self.run_dir is not None:
            router_block, workers = self._merged_worker_stats()
        else:
            router_block, workers = self.stats.snapshot(), None
        payload = {"router": router_block,
                   "replicas": replicas,
                   "fleet": {"counters": fleet_counters,
                             "models": self.manifest.names(),
                             "replicas_total": len(self._order),
                             "replicas_healthy": len(healthy),
                             "freshness_ms":
                                 max(freshness) if freshness else None},
                   "draining": self.draining}
        pressure = self.pressure_ms()
        payload["brownout"] = {
            "slo_ms": self.brownout_ms,
            "pressure_ms": round(pressure, 3),
            "active": self.brownout_ms > 0
            and pressure > self.brownout_ms}
        if self.outliers.enabled:
            payload["ejection"] = self.outliers.export()
        # fleet p50/p99 = the router tier's end-to-end window (merged
        # across every worker in sharded mode — any worker can answer)
        payload["fleet"]["latency_ms"] = payload["router"]["latency_ms"]
        if workers is not None:
            payload["workers"] = workers
        if self._view is not None:
            age = self._view.age_s()
            payload["view"] = {"generation": self._view.generation,
                               "age_s": round(age, 3)
                               if age is not None else None,
                               "read_errors": self._view.read_errors}
            rollout = self._view.doc().get("rollout")
            if rollout is not None:
                payload["rollout"] = rollout
        if self.deploy is not None:
            payload["rollout"] = self.deploy.stats()
        return payload

    def _merged_worker_stats(self):
        """Any worker answers /stats for the WHOLE front end: its live
        counters merged with every sibling's periodic dump (counters
        summed, latency windows concatenated for shard-wide p50/p99).
        Siblings are per-file best-effort — a worker mid-respawn just
        contributes its last dump or nothing."""
        exports = [self.stats.export()]
        workers = {str(self.worker_id): {"pid": os.getpid(),
                                         "live": True}}
        pattern = os.path.join(self.run_dir, "rworker-*.stats.json")
        for path in sorted(glob.glob(pattern)):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue            # mid-replace or mid-respawn
            wid = doc.get("worker")
            if wid is None or wid == self.worker_id:
                continue
            exports.append(doc.get("router") or {})
            workers[str(wid)] = {
                "pid": doc.get("pid"),
                "age_s": round(max(0.0, time.time()
                                   - float(doc.get("updated_at") or 0)),
                               3),
                "generation": doc.get("generation")}
        return Stats.merged_snapshot(exports), workers

    def dump_worker_stats(self):
        """Write this worker's counters next to the view file (the
        sibling-merge input and the worker-set readiness marker)."""
        if self.worker_id is None or self.run_dir is None:
            return None
        from ..resilience import atomic_write
        doc = {"worker": self.worker_id, "pid": os.getpid(),
               "updated_at": time.time(),
               "router": self.stats.export(),
               "generation": self._view.generation
               if self._view is not None else None}
        path = worker_stats_path(self.run_dir, self.worker_id)
        atomic_write(path, json.dumps(doc).encode("utf-8"),
                     fault_point="worker_stats_dump")
        return path

    def healthz_payload(self):
        healthy = self.healthy()
        return {"status": "draining" if self.draining else "ok",
                "replicas": len(self._order),
                "replicas_healthy": len(healthy),
                "healthy_ids": healthy}

    def _dump_loop(self):
        period = self._view.refresh_s if self._view is not None else 0.5
        while not self._stop_dump.wait(max(0.1, period)):
            try:
                self.dump_worker_stats()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Bind the public port, run one synchronous probe pass, start
        the health loop.  Returns self (``self.port`` holds the real
        port).  View mode starts NO probe/health machinery — the
        snapshot is the health signal — and instead dumps its counters
        (first dump immediately: the worker-set readiness marker)."""
        if self._server is not None:
            return self
        router = self

        class Handler(_Handler):
            rt = router

        server_cls = _ReuseportHTTPServer if self.reuse_port \
            else ThreadingHTTPServer
        self._server = server_cls((self.host, self.port), Handler)
        self._server.daemon_threads = False
        self._server.block_on_close = True
        self.port = self._server.server_address[1]
        if self._view is not None:
            self._sync_view()
            if self.worker_id is not None and self.run_dir is not None:
                self.dump_worker_stats()
                self._dump_thread = threading.Thread(
                    target=self._dump_loop, name="mxfleet-stats-dump",
                    daemon=True)
                self._dump_thread.start()
            return self
        self.probe()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="mxfleet-health", daemon=True)
        self._health_thread.start()
        return self

    def serve_forever(self):
        self.start()
        with self._life_lock:
            if self._aborted:       # drained before the loop started
                self._server.server_close()
                self._stopped.set()
                return
            self._serving = True
        try:
            self._server.serve_forever(poll_interval=0.1)
        finally:
            self._server.server_close()
            self._stopped.set()

    def serve_in_background(self):
        self.start()
        t = threading.Thread(target=self.serve_forever,
                             name="mxfleet-http", daemon=True)
        t.start()
        return self

    def drain_and_stop(self, timeout=60.0):
        """SIGTERM path: fence new work, wait out the router's own
        in-flight forwards, drain every replica through the controller,
        stop.  Idempotent."""
        self.draining = True
        if self.deploy is not None:
            # no rollout may fence/swap replicas the drain is stopping
            self.deploy.stop()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if all(v.inflight == 0 for v in self._views.values()):
                    break
            time.sleep(0.05)
        self._stop_health.set()
        self._stop_dump.set()
        try:
            # final counter dump so a sibling's post-drain /stats merge
            # still sees this worker's full ledger
            self.dump_worker_stats()
        except Exception:  # noqa: BLE001 — best-effort observability
            pass
        if self._controller is not None:
            self.replica_rcs = self._controller.drain(
                timeout=max(1.0, deadline - time.monotonic()))
        with self._life_lock:
            serving = self._serving
            if not serving:
                self._aborted = True
        if serving and self._server is not None:
            self._server.shutdown()

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT)):
        def _on_signal(signum, frame):
            threading.Thread(target=self.drain_and_stop,
                             name="mxfleet-drain", daemon=True).start()
        for sig in signals:
            signal.signal(sig, _on_signal)
        return self

    def wait_stopped(self, timeout=None):
        return self._stopped.wait(timeout)


class _ReuseportHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that binds with SO_REUSEPORT: N router
    workers listen on the SAME public port and the kernel balances new
    connections across them (established keep-alive connections stay
    with their worker — per-worker connection pools and the
    exactly-once retry discipline are untouched)."""

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):
            raise MXNetError(
                "SO_REUSEPORT is not available on this platform — the "
                "sharded front end needs Linux")
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        ThreadingHTTPServer.server_bind(self)


class _Handler(BaseHTTPRequestHandler):
    """Thin proxy handler onto the owning :class:`FleetRouter` (``rt``
    class attr, set by ``start()``)."""

    rt = None
    protocol_version = "HTTP/1.1"
    #: same rationale as the mxserve handler: bound idle keep-alive
    #: reads so block_on_close joins cannot wedge the drain
    timeout = 10.0

    def log_message(self, fmt, *args):
        pass

    def _reply_raw(self, status, body, ctype, extra=None):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (extra or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, status, payload):
        self._reply_raw(status, json.dumps(payload).encode("utf-8"),
                        "application/json")

    def do_GET(self):
        if self.path == "/healthz":
            self._reply(200, self.rt.healthz_payload())
        elif self.path == "/stats":
            self._reply(200, self.rt.stats_payload())
        else:
            self._reply(404, {"error": "unknown path %r" % self.path})

    def do_POST(self):
        if not self.path.startswith("/predict/"):
            self._reply(404, {"error": "unknown path %r" % self.path})
            return
        model = self.path[len("/predict/"):].strip("/")
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        fwd_headers = {"Content-Type":
                       self.headers.get("Content-Type")
                       or "application/json"}
        for h in ("X-MXTPU-Priority", "X-MXTPU-Deadline-Ms",
                  "X-MXTPU-Tenant", "X-MXTPU-Request-Id"):
            if self.headers.get(h) is not None:
                fwd_headers[h] = self.headers[h]
        status, data, ctype = self.rt.proxy_predict(model, body,
                                                    fwd_headers)
        extra = None
        if status == 429:
            # brownout shed: tell well-behaved clients when to come
            # back instead of letting them hammer a saturated fleet
            try:
                secs = json.loads(data.decode("utf-8")) \
                    .get("retry_after_s")
            except Exception:  # noqa: BLE001
                secs = None
            if secs is not None:
                extra = {"Retry-After":
                         str(max(1, int(round(float(secs)))))}
        self._reply_raw(status, data, ctype, extra=extra)
