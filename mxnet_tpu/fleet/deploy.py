"""Rolling hot swap across a fleet: one replica at a time, capacity
never below N-1, a bad epoch halts with most replicas untouched
(docs/how_to/fleet.md, "Rolling deployment").

:class:`RollingSwap` composes the single-daemon promote path
(``serving/deploy.py`` — every replica owns its own verify -> stage ->
swap -> probe pipeline behind ``POST /swap/<model>``) into a
fleet-level rollout:

1. **Watch** each directory-loaded model's checkpoint dir with the
   SAME verifier the replicas use (:func:`~..resilience.
   verify_promotion`) — a damaged publish never even starts a rollout.
2. **Roll** a verified new epoch one replica at a time:
   **fence** (the router holds new traffic off the replica — in-flight
   work finishes, the other N-1 replicas carry the fleet) -> **swap**
   (``POST /swap/<model>`` — the replica re-verifies the bytes itself,
   stages, swaps at its dispatch boundary, probes; defense in depth) ->
   **probe** (``/healthz`` + ``/stats`` must show the replica healthy
   AND serving the new epoch) -> **rejoin** (unfence).
3. **Halt on failure**: a replica that refuses the epoch (verification,
   validation or probe — it rolled itself back and still serves the old
   epoch) stops the rollout THERE: replicas not yet reached keep the
   old epoch, the fleet keeps serving, and ``/stats`` shows the halted
   rollout for the operator.

Per the fleet idiom this module is jax-FREE (stdlib + ``..base`` +
``..resilience`` only): it runs inside the router process, which must
never spin an XLA client.
"""
from __future__ import annotations

import json
import os
import threading

from ..base import MXNetError, get_env
from ..resilience import verify_promotion
from ..serving.deploy import ENV_SWAP_POLL_S  # noqa: F401 — shared knob

__all__ = ["RollingSwap"]


def _log_default(msg):
    import logging
    logging.getLogger(__name__).warning(msg)


class RollingSwap(object):
    """``models``: ``{model_name: checkpoint_directory}`` — the
    directory-loaded subset of the fleet manifest (prefix:epoch models
    have no stream to follow).  ``router``: the :class:`~.router.
    FleetRouter` owning replica addresses, fencing and /stats."""

    def __init__(self, router, models, prefix="checkpoint", poll_s=None,
                 http_timeout=120.0, log=None):
        if not models:
            raise MXNetError("RollingSwap needs at least one "
                             "checkpoint-directory model to watch")
        self.router = router
        self.models = {name: os.fspath(d) for name, d in models.items()}
        self.prefix = prefix
        self.poll_s = float(get_env(ENV_SWAP_POLL_S)
                            if poll_s is None else poll_s)
        self.http_timeout = float(http_timeout)
        self._log = log or _log_default
        #: model -> fleet-wide epoch (every replica agreed); seeded
        #: from the replicas' own /healthz on the first poll
        self._current = {}
        #: failed publishes already counted/halted, model -> (epoch,
        #: manifest-entry mark): held until the epoch is rewritten or
        #: a newer one appears — a bad epoch must not re-roll (and
        #: re-fence replicas) every poll
        self._rejected = {}
        self.counters = {"polls": 0, "rollouts": 0, "rejected": 0,
                         "halted": 0}
        self._progress = {"state": "idle"}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        router.deploy = self

    # -- observation -------------------------------------------------------
    def stats(self):
        with self._lock:
            out = dict(self.counters)
            out["state"] = dict(self._progress)
            out["watching"] = self._thread is not None and \
                self._thread.is_alive()
            out["models"] = dict(self._current)
        return out

    def _set_progress(self, **kw):
        with self._lock:
            self._progress = dict(kw)

    # -- replica HTTP ------------------------------------------------------
    def _replica_request(self, addr, method, path, payload=None):
        """One request to a replica -> (status, parsed payload).  Like
        the router's forwards: never retried (a /swap POST is not
        idempotent — the replica may already be swapping)."""
        import http.client
        conn = http.client.HTTPConnection(addr[0], addr[1],
                                          timeout=self.http_timeout)
        try:
            body = None
            headers = {}
            if payload is not None:
                body = json.dumps(payload).encode("utf-8")
                headers["Content-Type"] = "application/json"
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                doc = json.loads(data.decode("utf-8")) if data else {}
            except ValueError:
                doc = {"raw": data.decode("utf-8", "replace")}
            return resp.status, doc
        finally:
            conn.close()

    def _replica_epoch(self, addr, model):
        try:
            status, doc = self._replica_request(addr, "GET", "/healthz")
        except Exception:  # noqa: BLE001 — replica down
            return None
        if status != 200:
            return None
        return (doc.get("epochs") or {}).get(model)

    # -- the rollout -------------------------------------------------------
    def check_once(self):
        """One poll over every watched model; returns the outcomes
        (``{model: action}``)."""
        with self._lock:
            self.counters["polls"] += 1
        out = {}
        for model, directory in self.models.items():
            out[model] = self._check_model(model, directory)
        return out

    def _entry_mark(self, directory, epoch):
        """Identity of one publish (resilience.publish_mark — the SAME
        helper CheckpointWatcher keys on): a rewritten epoch re-enters,
        an unchanged failed one is held."""
        from ..resilience import publish_mark
        return publish_mark(directory, epoch, prefix=self.prefix)

    def _check_model(self, model, directory):
        epoch, problems = verify_promotion(directory,
                                           prefix=self.prefix)
        if epoch is None:
            return "no_checkpoint"
        current = self._current.get(model)
        if current is None:
            # adopt the fleet's own view: what the replicas already
            # serve (they loaded the newest intact epoch at bring-up)
            current = self._seed_current(model)
        if current is not None and epoch <= current and not problems:
            return "current"
        mark = self._entry_mark(directory, epoch)
        if problems:
            if self._rejected.get(model) != (epoch, mark):
                self._rejected[model] = (epoch, mark)
                self.counters["rejected"] += 1
                self._log("fleet rollout: REJECTING epoch %d of %r — "
                          "verification failed, fleet stays on %s: %s"
                          % (epoch, model, current,
                             "; ".join(problems)))
            return "rejected"
        if self._rejected.get(model) == (epoch, mark):
            # this publish already failed a rollout: hold until it is
            # rewritten or a newer epoch appears
            return "rejected"
        return self._rollout(model, epoch, current, mark)

    def _seed_current(self, model):
        addrs = self.router._addresses()
        epochs = [self._replica_epoch(addr, model)
                  for addr in addrs.values() if addr is not None]
        epochs = [e for e in epochs if e is not None]
        if not epochs:
            return None
        seed = min(epochs)          # the laggiest replica defines "done"
        self._current[model] = seed
        return seed

    def _rollout(self, model, epoch, current, mark=None):
        """Fence -> swap -> probe -> rejoin, one replica at a time."""
        self.counters["rollouts"] += 1
        addrs = self.router._addresses()
        order = sorted(addrs)
        done = []
        self._set_progress(state="rolling", model=model, epoch=epoch,
                           from_epoch=current, done=list(done),
                           total=len(order))
        for rid in order:
            if self._stop.is_set():
                self._set_progress(state="stopped", model=model,
                                   epoch=epoch, done=list(done))
                return "stopped"
            addr = addrs.get(rid)
            if addr is None:
                # a replica mid-respawn: its supervisor brings it back
                # on the NEW newest epoch (load_dir reads the manifest)
                continue
            if self._replica_epoch(addr, model) == epoch:
                done.append(rid)    # already there (e.g. respawned)
                continue
            fenced = False
            if len(order) > 1:
                try:
                    self.router.fence(rid)
                    fenced = True
                except MXNetError as e:
                    # transient (the other replicas are evicted right
                    # now): halt WITHOUT holding — the next poll
                    # retries once capacity is back
                    self._log("fleet rollout: cannot fence replica %s "
                              "(%s) — halting" % (rid, e))
                    self._halt(model, epoch, done, str(e))
                    return "halted"
            try:
                try:
                    status, doc = self._replica_request(
                        addr, "POST", "/swap/%s" % model,
                        {"epoch": epoch})
                except Exception as e:  # noqa: BLE001 — replica died
                    # TRANSPORT failure, not a refusal: the replica
                    # crashed/hung — its supervisor respawns it (on
                    # the new newest epoch) and the next poll resumes
                    # the rollout; holding here would freeze a healthy
                    # epoch out of the rest of the fleet forever
                    self._halt(model, epoch, done,
                               "replica %s unreachable mid-swap: %s"
                               % (rid, e))
                    return "halted"
                if status != 200:
                    # the replica refused (verify/validation/probe
                    # failed — it rolled itself back): halt with every
                    # later replica untouched on the old epoch
                    self._halt(model, epoch, done,
                               "replica %s refused epoch %d: %s"
                               % (rid, epoch,
                                  doc.get("problems") or doc), mark)
                    return "halted"
                if self._replica_epoch(addr, model) != epoch:
                    # inconsistent replica (200 but wrong epoch):
                    # retryable — do not hold the epoch fleet-wide
                    self._halt(model, epoch, done,
                               "replica %s reports the wrong epoch "
                               "after a 200 swap" % rid)
                    return "halted"
            finally:
                if fenced:
                    self.router.unfence(rid)
            done.append(rid)
            self._set_progress(state="rolling", model=model,
                               epoch=epoch, from_epoch=current,
                               done=list(done), total=len(order))
        self._current[model] = epoch
        self._rejected.pop(model, None)
        self._set_progress(state="complete", model=model, epoch=epoch,
                           done=list(done), total=len(order))
        self._log("fleet rollout: %r now serves epoch %d on %d "
                  "replica(s)" % (model, epoch, len(done)))
        return "complete"

    def _halt(self, model, epoch, done, reason, mark=None):
        """Stop the rollout here.  ``mark`` set = a replica REFUSED
        the epoch (its own verify/validate/probe said the bytes are
        bad): hold this publish so the poll loop does not re-roll it
        forever — a REWRITTEN or newer epoch re-enters normally.
        ``mark=None`` = a transport-level failure (crash, fence race):
        nothing said the epoch is bad, so the next poll retries."""
        self.counters["halted"] += 1
        if mark is not None:
            self._rejected[model] = (epoch, mark)
        self._set_progress(state="halted", model=model, epoch=epoch,
                           done=list(done), reason=str(reason))
        self._log("fleet rollout: HALTED promoting epoch %d of %r "
                  "after %d replica(s): %s"
                  % (epoch, model, len(done), reason))

    # -- the poll thread ---------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mxfleet-rollout", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def _loop(self):
        delay = self.poll_s
        while not self._stop.wait(delay):
            try:
                self.check_once()
                delay = self.poll_s
            except Exception as e:  # noqa: BLE001 — the tail must live
                delay = min(delay * 2.0, self.poll_s * 32.0)
                self._log("fleet rollout: poll failed (%s: %s) — "
                          "backing off to %.1fs"
                          % (type(e).__name__, e, delay))
