"""Shared fleet view: ONE controller-side prober, N stateless router
workers (the sharded front end, docs/how_to/fleet.md).

PR 11 measured the boundary: one Python router process caps
dispatch-bound traffic at ~1.2k req/s no matter how many replicas sit
behind it — the bottleneck is the router's own GIL + accept loop, not
routing policy.  The fix is the classic SO_REUSEPORT shard: N router
WORKER processes all listen on the SAME public port (the kernel hashes
each connection to one worker at SYN time, so an established keep-alive
connection stays put), and the single-process ceiling multiplies by the
worker count.

What keeps N workers coherent without coordination is this module's
split:

- :class:`FleetViewPublisher` — the ONE prober.  It wraps a non-serving
  :class:`~.router.FleetRouter` (probe loop + fence state + the N-1
  capacity floor, reused verbatim) and publishes the routing inputs —
  replica addresses, health, per-replica ``/stats``, the fenced set —
  into an atomically-replaced JSON snapshot stamped with a monotonically
  increasing **generation** counter.  Fencing (rolling swaps, autoscale
  scale-down) happens HERE, controller-side; the snapshot is how workers
  learn of it.
- :class:`FleetViewReader` — the worker-side consumer: re-reads the
  snapshot on a refresh period, keeps the last good document when a read
  races the publisher or the publisher is briefly gone (a worker on a
  stale generation keeps routing to the last-known-healthy set — SAFE,
  because a replica that died since then is absorbed by the router's
  keyed one-resend-elsewhere discipline), and never moves BACKWARD in
  generations.
- :class:`RouterWorkerSet` — spawns + supervises the N
  ``tools/fleet.py router-worker`` processes (same exit-code discipline
  as the replica controller: unexpected deaths respawn within a streak
  budget, drains respawn nothing).

Why a JSON file and not mmap: the snapshot is kB-scale at any plausible
fleet size, ``os.replace`` gives atomic whole-document swaps with zero
reader locking, and the file doubles as a live debugging surface
(``cat run/fleet-view.json``).  mmap would buy zero-copy reads the
kB scale does not need, at the cost of hand-rolled torn-read handling.

Workers never probe and never talk to each other; each keeps its OWN
:class:`~..serving.frontend.Stats` counters and periodically dumps them
next to the view file, so ANY worker can answer ``/stats`` for the
whole front end by merging the sibling dumps with its live counters
(see ``FleetRouter.stats_payload`` in view mode).
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from ..base import MXNetError, get_env, register_env

__all__ = ["FleetViewPublisher", "FleetViewReader", "RouterWorkerSet",
           "OutlierDetector", "reserve_port", "worker_stats_path",
           "default_fleet_py", "VIEW_BASENAME", "ENV_FLEET_WORKERS",
           "ENV_FLEET_VIEW_REFRESH_S", "ENV_FLEET_EJECT_X"]

ENV_FLEET_WORKERS = register_env(
    "MXTPU_FLEET_WORKERS", default=1,
    doc="Router worker processes sharing the public port via "
        "SO_REUSEPORT (`tools/fleet.py serve --workers`); 1 keeps the "
        "single-process in-line router")
ENV_FLEET_VIEW_REFRESH_S = register_env(
    "MXTPU_FLEET_VIEW_REFRESH_S", default=0.25,
    doc="Shared-fleet-view cadence: the controller-side prober "
        "publishes the routing snapshot and each router worker re-reads "
        "it (and dumps its own counters) this often")
ENV_FLEET_EJECT_X = register_env(
    "MXTPU_FLEET_EJECT_X", default=0.0,
    doc="Gray-failure outlier ejection: temporarily eject a replica "
        "whose recent-p99 latency EWMA exceeds this multiple of the "
        "fleet median (or whose forward errors streak), folded into "
        "the published healthy bit like fencing; 0 disables ejection")

#: the snapshot file name under the fleet run dir
VIEW_BASENAME = "fleet-view.json"

#: what a reader answers before the first successful snapshot read —
#: nothing routable, which the worker surfaces as 503 (identical to a
#: fleet whose replicas have not probed healthy yet)
_EMPTY_DOC = {"generation": 0, "published_at": 0.0, "replicas": {},
              "fenced": [], "models": []}


def reserve_port(host="127.0.0.1", port=0):
    """Claim the fleet's public port for the worker shard: bind a
    SO_REUSEPORT socket WITHOUT listening and keep it open for the
    fleet's lifetime.

    A bound-but-not-listening socket takes no connections (the kernel
    only balances across *listening* reuseport sockets), so the parent
    holds the port steady — ``port=0`` resolves the ephemeral pick
    once, and the port cannot be stolen by an unrelated process in the
    gap while a dead worker respawns.  Returns ``(socket, port)``; the
    caller owns closing the socket."""
    if not hasattr(socket, "SO_REUSEPORT"):
        raise MXNetError(
            "SO_REUSEPORT is not available on this platform — the "
            "sharded front end (--workers > 1) needs Linux")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((host, int(port)))
    except OSError:
        sock.close()
        raise
    return sock, sock.getsockname()[1]


def worker_stats_path(run_dir, worker_id):
    """Where router worker ``worker_id`` dumps its counters (and what
    any sibling merges on ``/stats``)."""
    return os.path.join(run_dir, "rworker-%d.stats.json" % int(worker_id))


def default_fleet_py():
    """``tools/fleet.py`` next to this checkout (the router-worker
    binary)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "fleet.py")


class OutlierDetector(object):
    """Controller-side gray-failure detection (Envoy-style outlier
    ejection, per "The Tail at Scale"): the ONE prober tracks each
    replica's recent-p99 latency as an EWMA plus its forward-error
    streak, and temporarily EJECTS a replica that has gone
    slow-but-alive — p99 EWMA beyond ``MXTPU_FLEET_EJECT_X`` times the
    fleet median, or ``error_streak`` consecutive probe passes with new
    transport errors.  Ejection folds into the published view's healthy
    bit exactly like fencing, so every router worker stops routing to
    the outlier within one snapshot refresh.

    Guard rails:

    - **max-eject fraction / N-1 floor**: at most ``max_eject_frac`` of
      the routable set may be ejected at once, and never the last
      routable replica (``eject_blocked_floor`` counts refusals) — a
      detector gone wrong must degrade to the old behavior, not take
      the fleet down;
    - **half-open re-probe**: after ``hold_s`` the replica rejoins
      routing on probation (its EWMA is reset — fresh eyes); the next
      pass with a latency sample either re-ejects it (still an outlier)
      or reinstates it for good (``eject_rejoins``).

    The latency signal is each replica's ``latency_ms.p99_recent`` from
    its own ``/stats`` (a small-window tail percentile — see
    ``Stats.RECENT_WINDOW``), NOT the probe round-trip: a gray-failing
    replica answers its cheap ``/healthz`` promptly while its serving
    path crawls."""

    def __init__(self, eject_x=None, alpha=0.4, min_samples=3,
                 max_eject_frac=0.5, hold_s=2.0, error_streak=3):
        self.eject_x = float(get_env(ENV_FLEET_EJECT_X)
                             if eject_x is None else eject_x)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.max_eject_frac = float(max_eject_frac)
        self.hold_s = float(hold_s)
        self.error_streak = int(error_streak)
        self._lock = threading.Lock()
        self._ewma = {}          # rid -> (ewma_ms, sample_count)
        self._errors = {}        # rid -> last cumulative error count
        self._streaks = {}       # rid -> consecutive error passes
        self._ejected = {}       # rid -> eject deadline (monotonic)
        self._half_open = set()  # rids on post-eject probation
        self.counters = {"ejects": 0, "eject_rejoins": 0,
                         "eject_blocked_floor": 0}

    @property
    def enabled(self):
        return self.eject_x > 0.0

    def ejected(self, now=None):
        """Rids currently held out of routing (half-open rids are
        routable — that IS the re-probe)."""
        if not self.enabled:
            return set()
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [r for r, until in self._ejected.items()
                       if now >= until]
            for rid in expired:
                del self._ejected[rid]
                self._half_open.add(rid)
                # probation judges fresh samples, not the slow spell
                # that caused the eject
                self._ewma.pop(rid, None)
                self._streaks.pop(rid, None)
            return set(self._ejected)

    def _median(self, rids):
        vals = sorted(self._ewma[r][0] for r in rids
                      if r in self._ewma
                      and self._ewma[r][1] >= self.min_samples)
        if not vals:
            return None
        return vals[len(vals) // 2]

    def update(self, routable, latency_ms, errors, now=None):
        """One detector pass, fed by the prober: ``routable`` = rids
        routable before ejection, ``latency_ms`` = {rid: recent p99},
        ``errors`` = {rid: cumulative forward+probe error count}.
        Returns the counter increments for this pass (the router folds
        them into its /stats counters)."""
        if not self.enabled:
            return {}
        now = time.monotonic() if now is None else now
        held = self.ejected(now)        # also promotes expired -> half-open
        events = {"ejects": 0, "eject_rejoins": 0,
                  "eject_blocked_floor": 0}
        with self._lock:
            gone = [r for r in self._ewma if r not in routable
                    and r not in held]
            for rid in gone:            # evicted/scaled-down: forget it
                self._ewma.pop(rid, None)
                self._streaks.pop(rid, None)
                self._errors.pop(rid, None)
                self._half_open.discard(rid)
            for rid in routable:
                if rid in held:
                    continue
                sample = latency_ms.get(rid)
                if sample is not None:
                    ewma, n = self._ewma.get(rid, (float(sample), 0))
                    ewma += self.alpha * (float(sample) - ewma)
                    self._ewma[rid] = (ewma, n + 1)
                errs = int(errors.get(rid, 0))
                last = self._errors.get(rid)
                self._errors[rid] = errs
                if last is not None and errs > last:
                    self._streaks[rid] = self._streaks.get(rid, 0) + 1
                else:
                    self._streaks[rid] = 0
            active = [r for r in routable if r not in held]
            median = self._median(active)
            max_eject = min(int(self.max_eject_frac * len(active)),
                            len(active) - 1)
            for rid in active:
                outlier = self._streaks.get(rid, 0) >= self.error_streak
                ewma, n = self._ewma.get(rid, (0.0, 0))
                if not outlier and median and n >= self.min_samples:
                    outlier = ewma > self.eject_x * median
                if rid in self._half_open:
                    if n < 1:
                        continue        # no fresh sample yet: stay open
                    self._half_open.discard(rid)
                    if not outlier:
                        self.counters["eject_rejoins"] += 1
                        events["eject_rejoins"] += 1
                        continue        # reinstated; fall through ejects
                if not outlier:
                    continue
                if len(self._ejected) + 1 > max_eject:
                    self.counters["eject_blocked_floor"] += 1
                    events["eject_blocked_floor"] += 1
                    continue
                self._ejected[rid] = now + self.hold_s
                self.counters["ejects"] += 1
                events["ejects"] += 1
        return events

    def export(self, now=None):
        """Per-rid eject state for the published view / stats table."""
        now = time.monotonic() if now is None else now
        held = self.ejected(now)
        with self._lock:
            out = {}
            for rid in set(self._ewma) | held | set(self._half_open):
                ewma = self._ewma.get(rid)
                out[rid] = {
                    "ejected": rid in held,
                    "eject_left_s":
                        round(self._ejected[rid] - now, 3)
                        if rid in self._ejected else None,
                    "half_open": rid in self._half_open,
                    "latency_ewma_ms":
                        round(ewma[0], 3) if ewma else None}
            return out


class FleetViewPublisher(object):
    """The one prober: probe the fleet through ``router`` (a
    :class:`~.router.FleetRouter` that never serves HTTP — the parent
    process builds it purely for its probe loop, fence state and
    capacity-floor checks) and publish the routing snapshot to
    ``path`` after every pass."""

    def __init__(self, router, path, period_s=None, log=None):
        self.router = router
        self.path = path
        self.period_s = float(get_env(ENV_FLEET_VIEW_REFRESH_S)
                              if period_s is None else period_s)
        self.generation = 0
        self.publishes = 0
        self.publish_errors = 0
        # serializes publish_once: the publish loop owns the cadence,
        # but the autoscaler (and tests) call publish_once directly to
        # push a fence out early — two interleaved passes would race
        # the generation bump and could write snapshots out of order
        self._lock = threading.Lock()
        self._log = log or (lambda msg: None)
        self._stop = threading.Event()
        self._thread = None

    def publish_once(self, probe=True):
        """One probe pass + one atomic snapshot write; returns the
        published document."""
        from ..resilience import atomic_write
        if probe:
            self.router.probe()
        with self._lock:
            self.generation += 1
            doc = {"generation": self.generation,
                   "published_at": time.time(),
                   "heartbeat_s": self.router.heartbeat_s,
                   "evict_s": self.router.evict_s,
                   "replicas": self.router.view_export(),
                   "fenced": list(self.router.fenced()),
                   "models": self.router.manifest.names()}
            if self.router.deploy is not None:
                doc["rollout"] = self.router.deploy.stats()
            atomic_write(self.path, json.dumps(doc).encode("utf-8"),
                         fault_point="view_publish")
            self.publishes += 1
        return doc

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.publish_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                with self._lock:
                    self.publish_errors += 1
                self._log("fleet view: publish failed (%s: %s)"
                          % (type(e).__name__, e))

    def start(self):
        """Publish one synchronous snapshot (workers started right
        after must never read an absent file), then keep publishing on
        the period."""
        if self._thread is not None:
            return self
        self.publish_once()
        self._thread = threading.Thread(target=self._loop,
                                        name="mxfleet-view-pub",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return self

    def stats(self):
        return {"generation": self.generation,
                "publishes": self.publishes,
                "publish_errors": self.publish_errors,
                "period_s": self.period_s}


class FleetViewReader(object):
    """Worker-side snapshot consumer: cheap cached reads on a refresh
    period, last-good-document semantics on any read failure (torn
    replace race, publisher briefly absent), generations never move
    backward."""

    def __init__(self, path, refresh_s=None):
        self.path = path
        self.refresh_s = float(get_env(ENV_FLEET_VIEW_REFRESH_S)
                               if refresh_s is None else refresh_s)
        self._lock = threading.Lock()
        self._doc = None
        self._read_at = 0.0
        self.reads = 0
        self.read_errors = 0

    def doc(self, force=False):
        """The current view document (re-read at most every
        ``refresh_s`` unless forced); never raises — a worker must keep
        routing on the last good snapshot through publisher hiccups."""
        now = time.monotonic()
        with self._lock:
            if not force and self._doc is not None \
                    and now - self._read_at < self.refresh_s:
                return self._doc
        try:
            with open(self.path) as f:
                fresh = json.load(f)
        except (OSError, ValueError):
            with self._lock:
                self.read_errors += 1
                self._read_at = now     # do not hammer a missing file
                return self._doc if self._doc is not None else _EMPTY_DOC
        with self._lock:
            self.reads += 1
            self._read_at = now
            if self._doc is None or int(fresh.get("generation", 0)) >= \
                    int(self._doc.get("generation", 0)):
                self._doc = fresh
            return self._doc

    @property
    def generation(self):
        return int(self.doc().get("generation", 0))

    def age_s(self):
        """Wall-clock age of the held snapshot (the worker's staleness
        gauge; same host, so wall clocks agree)."""
        published = float(self.doc().get("published_at", 0.0))
        if not published:
            return None
        return max(0.0, time.time() - published)

    def replicas(self):
        """{rid: entry} with the ORIGINAL replica ids (JSON stringifies
        dict keys; each entry carries its real ``id``)."""
        out = {}
        for key, ent in (self.doc().get("replicas") or {}).items():
            out[ent.get("id", key)] = ent
        return out

    def fenced(self):
        return list(self.doc().get("fenced") or [])


class _Worker(object):
    """Bookkeeping for one supervised router-worker process."""

    __slots__ = ("id", "argv", "log_path", "proc", "restarts", "streak",
                 "state", "last_rc", "spawned_at")

    def __init__(self, wid, argv, log_path):
        self.id = wid
        self.argv = argv
        self.log_path = log_path
        self.proc = None
        self.restarts = 0
        self.streak = 0
        self.state = "starting"
        self.last_rc = None
        self.spawned_at = None

    def snapshot(self):
        return {"id": self.id, "state": self.state,
                "pid": self.proc.pid if self.proc is not None else None,
                "restarts": self.restarts, "last_rc": self.last_rc}


class RouterWorkerSet(object):
    """Spawn + supervise N ``tools/fleet.py router-worker`` processes,
    all binding the same reserved public port via SO_REUSEPORT.

    Same supervision discipline as :class:`~.controller
    .ReplicaController`: an unexpected death respawns within a streak
    budget (``stable_s`` of uptime resets the streak), a drain respawns
    nothing.  Workers are pure-host processes (no jax) — a respawn is
    milliseconds, and the kernel keeps balancing new connections over
    the survivors meanwhile."""

    def __init__(self, manifest_path, view_path, host, port, workers,
                 run_dir, slo_ms=0.0, request_timeout=60.0,
                 spill_queue=None, python=None, fleet_py=None,
                 max_restarts=3, backoff=0.5, stable_s=30.0, log=None):
        if int(workers) < 1:
            raise MXNetError("a worker set needs at least one worker")
        self.run_dir = run_dir
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.stable_s = float(stable_s)
        self._log = log or (lambda msg: None)
        self._lock = threading.Lock()
        self._draining = False
        self._threads = []
        os.makedirs(run_dir, exist_ok=True)
        python = python or sys.executable
        fleet_py = fleet_py or default_fleet_py()
        self.workers = []
        for i in range(int(workers)):
            argv = [python, fleet_py, "router-worker",
                    "--manifest-file", manifest_path,
                    "--view", view_path,
                    "--host", host, "--port", str(int(port)),
                    "--worker-id", str(i),
                    "--run-dir", run_dir,
                    "--slo-ms", str(float(slo_ms)),
                    "--request-timeout", str(float(request_timeout))]
            if spill_queue is not None:
                argv += ["--spill-queue", str(int(spill_queue))]
            self.workers.append(_Worker(
                i, argv, os.path.join(run_dir, "rworker-%d.log" % i)))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        for w in self.workers:
            # a stale dump must not satisfy wait_ready before the new
            # process actually bound the port
            try:
                os.unlink(worker_stats_path(self.run_dir, w.id))
            except OSError:
                pass
            self._spawn(w)
            t = threading.Thread(target=self._supervise, args=(w,),
                                 name="mxfleet-rworker-sup-%d" % w.id,
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _spawn(self, w):
        log_f = open(w.log_path, "ab")
        try:
            w.proc = subprocess.Popen(w.argv, stdout=log_f, stderr=log_f)
        finally:
            log_f.close()
        w.spawned_at = time.monotonic()
        w.state = "starting"
        self._log("fleet: router worker %d spawned (pid %d)"
                  % (w.id, w.proc.pid))

    def _supervise(self, w):
        while True:
            rc = w.proc.wait()
            with self._lock:
                w.last_rc = rc
                if self._draining:
                    w.state = "drained" if rc == 0 else "exited"
                    return
                if time.monotonic() - w.spawned_at >= self.stable_s:
                    w.streak = 0
                if w.streak >= self.max_restarts:
                    w.state = "failed"
                    self._log("fleet: router worker %d exit rc=%s — "
                              "restart budget (%d) exhausted"
                              % (w.id, rc, self.max_restarts))
                    return
                w.streak += 1
                w.restarts += 1
            self._log("fleet: router worker %d exit rc=%s — relaunch "
                      "%d/%d" % (w.id, rc, w.streak, self.max_restarts))
            if self.backoff > 0:
                time.sleep(self.backoff)
            with self._lock:
                if self._draining:
                    w.state = "exited"
                    return
                try:
                    os.unlink(worker_stats_path(self.run_dir, w.id))
                except OSError:
                    pass
                self._spawn(w)

    # -- observation -------------------------------------------------------
    def ready(self):
        """Worker ids whose first stats dump landed (a worker dumps
        immediately after binding the shared port — the readiness
        marker)."""
        out = []
        for w in self.workers:
            if os.path.exists(worker_stats_path(self.run_dir, w.id)):
                if w.state == "starting":
                    w.state = "serving"
                out.append(w.id)
        return out

    def wait_ready(self, timeout=60.0):
        deadline = time.monotonic() + timeout
        while True:
            ready = self.ready()
            if len(ready) == len(self.workers):
                return ready
            with self._lock:
                failed = [w.id for w in self.workers
                          if w.state == "failed"]
            if failed:
                raise MXNetError(
                    "router worker(s) %s failed during bring-up — see "
                    "logs under %r" % (failed, self.run_dir))
            if time.monotonic() > deadline:
                raise MXNetError(
                    "router workers %s never became ready within %.0fs"
                    % (sorted(set(w.id for w in self.workers)
                              - set(ready)), timeout))
            time.sleep(0.05)

    def snapshot(self):
        with self._lock:
            return [w.snapshot() for w in self.workers]

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=30.0):
        """SIGTERM every worker (each fences new work, finishes its
        in-flight forwards, exits 0), wait, return {id: rc}."""
        with self._lock:
            self._draining = True
            procs = [(w, w.proc) for w in self.workers
                     if w.proc is not None]
        for w, proc in procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:     # pragma: no cover — just died
                    pass
        deadline = time.monotonic() + timeout
        rcs = {}
        for w, proc in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                rcs[w.id] = proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                rcs[w.id] = proc.wait()
                self._log("fleet: router worker %d did not drain in "
                          "%.0fs — killed" % (w.id, timeout))
        return rcs

    def kill(self):
        """SIGKILL everything (test cleanup, not a drain)."""
        with self._lock:
            self._draining = True
        for w in self.workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.kill()
                w.proc.wait()
