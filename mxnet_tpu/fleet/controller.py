"""Replica lifecycle: spawn N ``tools/serve.py`` daemons, supervise,
respawn.

Each replica is a REAL serving daemon in its own OS process (its own
XLA client, its own GIL, its own device subset via
``manifest.replica_device_env``), launched with plain ``subprocess``
exactly like ``tools/supervise.py`` launches training — and supervised
by the same exit-code discipline, extended for serving:

- rc 85 (preempt) / 87 (watchdog: a wedged forward was aborted) are
  RESUMABLE: relaunch with ``MXTPU_RESUME=1`` in the child env.
- ANY other unexpected death (SIGKILL, OOM, crash — a serving fleet
  treats replica death as capacity loss, not job failure) also
  relaunches, without the resume env.
- a relaunch streak is budgeted (``max_restarts``); a replica that
  stays up ``stable_s`` seconds resets its streak, so transient deaths
  over a long-lived fleet never accumulate into a permanent hole (the
  mxdata respawn-budget lesson).  A replica whose streak exhausts is
  left dead in state ``failed`` — the router routes around it.
- during a fleet drain nothing is relaunched; each replica gets the
  SIGTERM forwarded and drains to rc 0 on its own (the mxserve
  contract).

Respawned replicas come back WARM: the controller passes the AOT warm
store as ``MXTPU_COMPILE_CACHE``, so ``--warmup`` loads every (model,
bucket) program from disk instead of XLA (docs/how_to/fleet.md).
"""
from __future__ import annotations

import os
import signal
import subprocess
import threading
import time

from ..base import MXNetError
from ..resilience import PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE
from .manifest import default_serve_py, replica_device_env

__all__ = ["Replica", "ReplicaController"]

RESUME_ENV = "MXTPU_RESUME"         # the supervise.py relaunch contract


class Replica(object):
    """One supervised serving daemon (bookkeeping only — the process
    itself is a ``subprocess.Popen``)."""

    __slots__ = ("id", "argv", "env", "port_file", "log_path", "proc",
                 "port", "restarts", "streak", "state", "last_rc",
                 "spawned_at", "affinity")

    def __init__(self, rid, argv, env, port_file, log_path,
                 affinity=None):
        self.id = rid
        self.argv = argv
        self.env = env
        self.port_file = port_file
        self.log_path = log_path
        self.proc = None
        self.port = None
        self.restarts = 0           # lifetime relaunch count (stats)
        self.streak = 0             # consecutive relaunches (the budget)
        self.state = "starting"
        self.last_rc = None
        self.spawned_at = None
        self.affinity = affinity

    def snapshot(self):
        return {"id": self.id, "state": self.state, "port": self.port,
                "pid": self.proc.pid if self.proc is not None else None,
                "restarts": self.restarts, "last_rc": self.last_rc}


class ReplicaController(object):
    """Spawns ``manifest.replicas`` daemons and keeps them alive."""

    def __init__(self, manifest, run_dir, serve_py=None, python=None,
                 warm_store=None, max_restarts=3, backoff=0.5,
                 stable_s=30.0, cpu_affinity=None, extra_env=None,
                 extra_env_by_rid=None, log=None):
        self.manifest = manifest
        self.run_dir = run_dir
        self.serve_py = serve_py or default_serve_py()
        self.python = python
        self.warm_store = warm_store
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.stable_s = float(stable_s)
        self.extra_env = dict(extra_env or {})
        #: {rid: {NAME: VALUE}} — per-replica env on top of extra_env;
        #: how a drill arms a fault (e.g. MXTPU_FAULTS=slow_replica:N)
        #: on exactly ONE replica of the fleet
        self.extra_env_by_rid = {int(k): dict(v) for k, v
                                 in (extra_env_by_rid or {}).items()}
        self._log = log or (lambda msg: None)
        self._lock = threading.Lock()
        self._draining = False
        self._threads = []
        os.makedirs(run_dir, exist_ok=True)
        if cpu_affinity is None:
            # auto: partition host cores iff the replicas are CPU-pinned
            # co-tenants that would otherwise fight over one thread pool
            cpu_affinity = (manifest.device_sets == "cpu"
                            and manifest.replicas > 1)
        affinities = self._affinity_sets(manifest.replicas) \
            if cpu_affinity else [None] * manifest.replicas
        self.replicas = []
        for i in range(manifest.replicas):
            self.replicas.append(self._make_replica(
                i, affinity=affinities[i]))

    def _make_replica(self, rid, affinity=None):
        port_file = os.path.join(self.run_dir, "replica-%d.port" % rid)
        log_path = os.path.join(self.run_dir, "replica-%d.log" % rid)
        argv = self.manifest.serve_argv(self.serve_py,
                                        port_file=port_file, port=0,
                                        python=self.python)
        env = dict(os.environ)
        env.update(replica_device_env(self.manifest.device_sets, rid))
        env.update(self.extra_env)
        env.update(self.extra_env_by_rid.get(rid, {}))
        if self.warm_store:
            env["MXTPU_COMPILE_CACHE"] = self.warm_store
        return Replica(rid, argv, env, port_file, log_path,
                       affinity=affinity)

    @staticmethod
    def _affinity_sets(n):
        """Partition this process's CPU set into ``n`` contiguous
        chunks (replica *i* -> chunk *i*); hosts with fewer cores than
        replicas share everything (nothing to partition)."""
        if not hasattr(os, "sched_getaffinity"):
            return [None] * n       # pragma: no cover — non-Linux
        cores = sorted(os.sched_getaffinity(0))
        if len(cores) < 2 * n:
            return [None] * n
        per = len(cores) // n
        return [set(cores[i * per:(i + 1) * per]) if i < n - 1
                else set(cores[(n - 1) * per:]) for i in range(n)]

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        for rep in self.replicas:
            self._spawn(rep, resume=False)
            self._watch(rep)
        return self

    def _watch(self, rep):
        t = threading.Thread(target=self._supervise, args=(rep,),
                             name="mxfleet-sup-%d" % rep.id,
                             daemon=True)
        t.start()
        self._threads.append(t)
        return t

    # -- autoscaling (fleet/autoscale.py) ----------------------------------
    def add_replica(self):
        """Scale-up: spawn ONE new replica (next free id) and supervise
        it like the rest.  It comes up warm via the AOT store and joins
        routing the moment its port file appears and a probe succeeds.
        Dynamic replicas get no CPU pinning — the boot-time core
        partition is not re-balanced under scale."""
        with self._lock:
            if self._draining:
                raise MXNetError("fleet is draining — no scale-up")
            rid = max((r.id for r in self.replicas), default=-1) + 1
            rep = self._make_replica(rid)
            self.replicas.append(rep)
        self._spawn(rep, resume=False)
        self._watch(rep)
        return rep

    def stop_replica(self, rid, timeout=30.0):
        """Scale-down endpoint: SIGTERM ONE replica (it drains its
        accepted work and exits 0 — the mxserve contract) and never
        respawn it.  The CALLER owns the safety dance first: fence the
        replica at the router/publisher (the capacity floor is checked
        there) and wait out its queue — this method just retires the
        process.  Returns the exit code."""
        with self._lock:
            rep = next((r for r in self.replicas if r.id == rid), None)
            if rep is None:
                raise MXNetError("no replica %s to stop" % (rid,))
            rep.state = "scaling_down"
            proc = rep.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:         # pragma: no cover — just died
                pass
        rc = None
        if proc is not None:
            try:
                rc = proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
                self._log("fleet: replica %d did not drain in %.0fs on "
                          "scale-down — killed" % (rep.id, timeout))
        with self._lock:
            rep.state = "scaled_down"
            rep.last_rc = rc
        # a scaled-down replica's port must never route again
        try:
            os.unlink(rep.port_file)
        except OSError:
            pass
        self._log("fleet: replica %d scaled down (rc=%s)" % (rid, rc))
        return rc

    def _spawn(self, rep, resume):
        env = dict(rep.env)
        if resume:
            env[RESUME_ENV] = "1"
        # a stale port file must never route traffic to a dead port
        try:
            os.unlink(rep.port_file)
        except OSError:
            pass
        rep.port = None
        log_f = open(rep.log_path, "ab")
        try:
            rep.proc = subprocess.Popen(rep.argv, env=env,
                                        stdout=log_f, stderr=log_f)
        finally:
            log_f.close()           # the child holds its own fd now
        rep.spawned_at = time.monotonic()
        rep.state = "starting"
        if rep.affinity:
            try:
                os.sched_setaffinity(rep.proc.pid, rep.affinity)
            except OSError:  # pragma: no cover — race with child death
                pass
        self._log("fleet: replica %d spawned (pid %d)"
                  % (rep.id, rep.proc.pid))

    def _supervise(self, rep):
        """One thread per replica: wait, classify the exit, relaunch
        per the policy above."""
        while True:
            rc = rep.proc.wait()
            with self._lock:
                rep.last_rc = rc
                if self._draining:
                    rep.state = "drained" if rc == 0 else "exited"
                    return
                if rep.state in ("scaling_down", "scaled_down"):
                    # the autoscaler retired this replica on purpose —
                    # its death is the plan, not a capacity loss
                    rep.state = "scaled_down"
                    return
                lived = time.monotonic() - rep.spawned_at
                if lived >= self.stable_s:
                    rep.streak = 0
                if rep.streak >= self.max_restarts:
                    rep.state = "failed"
                    self._log("fleet: replica %d exit rc=%s — restart "
                              "budget (%d) exhausted, leaving dead"
                              % (rep.id, rc, self.max_restarts))
                    return
                rep.streak += 1
                rep.restarts += 1
            resumable = rc in (PREEMPT_EXIT_CODE, WATCHDOG_EXIT_CODE)
            self._log("fleet: replica %d exit rc=%s (%s) — relaunch "
                      "%d/%d%s" % (rep.id, rc,
                                   "resumable" if resumable else "death",
                                   rep.streak, self.max_restarts,
                                   " with %s=1" % RESUME_ENV
                                   if resumable else ""))
            if self.backoff > 0:
                time.sleep(self.backoff)
            with self._lock:
                if self._draining:
                    rep.state = "exited"
                    return
                if rep.state in ("scaling_down", "scaled_down"):
                    rep.state = "scaled_down"
                    return
                self._spawn(rep, resume=resumable)

    # -- observation -------------------------------------------------------
    def ports(self):
        """{replica id: port or None} — a replica's port appears once
        its daemon finished warmup and wrote the port file (re-read
        after every respawn: ephemeral ports change)."""
        out = {}
        with self._lock:
            reps = list(self.replicas)
        for rep in reps:
            if rep.state == "scaled_down":
                continue            # retired on purpose — never routes
            if rep.port is None and os.path.exists(rep.port_file):
                try:
                    with open(rep.port_file) as f:
                        rep.port = int(f.read().split(":")[1])
                    if rep.state == "starting":
                        rep.state = "serving"
                except (OSError, ValueError, IndexError):
                    rep.port = None
            out[rep.id] = rep.port
        return out

    def snapshot(self):
        self.ports()
        with self._lock:
            reps = list(self.replicas)
        return [rep.snapshot() for rep in reps]

    def wait_ready(self, timeout=300.0):
        """Block until every replica wrote its port file (i.e. finished
        its warmup and is accepting); raises on timeout or if a replica
        fails permanently first."""
        deadline = time.monotonic() + timeout
        while True:
            ports = self.ports()
            if all(p is not None for p in ports.values()):
                return ports
            if self._draining:
                # a fleet-wide drain landed during bring-up: replicas
                # drained to rc 0 and will never write port files —
                # waiting out the timeout would just hang the drain
                raise MXNetError("fleet drained during bring-up")
            with self._lock:
                failed = [r.id for r in self.replicas
                          if r.state == "failed"]
            if failed:
                raise MXNetError(
                    "replica(s) %s failed during bring-up — see logs "
                    "under %r" % (failed, self.run_dir))
            if time.monotonic() > deadline:
                raise MXNetError(
                    "replicas %s never became ready within %.0fs"
                    % ([i for i, p in ports.items() if p is None],
                       timeout))
            time.sleep(0.1)

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=60.0):
        """Fleet-wide drain: forward SIGTERM to every live replica
        (each finishes its accepted work and exits 0 — the mxserve
        contract), wait, return {id: rc}.  Stops all relaunching."""
        with self._lock:
            self._draining = True
            procs = [(rep, rep.proc) for rep in self.replicas
                     if rep.proc is not None]
        for rep, proc in procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:     # pragma: no cover — just died
                    pass
        deadline = time.monotonic() + timeout
        rcs = {}
        for rep, proc in procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                rcs[rep.id] = proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                rcs[rep.id] = proc.wait()
                self._log("fleet: replica %d did not drain in %.0fs — "
                          "killed" % (rep.id, timeout))
        return rcs

    def kill(self):
        """SIGKILL everything (test cleanup, not a drain)."""
        with self._lock:
            self._draining = True
            reps = list(self.replicas)
        for rep in reps:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
                rep.proc.wait()
