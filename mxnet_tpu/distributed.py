"""Multi-process distributed runtime (the ps-lite/tracker replacement).

The reference builds clusters from three process roles — scheduler, server,
worker — wired over ZMQ with ``DMLC_*`` envs (``tools/launch.py:46-70``,
``python/mxnet/kvstore_server.py:58-68``, ``ps-lite``).  The TPU-native
design needs exactly one role: N symmetric JAX processes joined into one
global device topology by ``jax.distributed.initialize``; reductions then
ride XLA collectives over ICI/DCN instead of RPC to server shards
(SURVEY §2.3).

This module owns process-group bring-up and the low-level collective
primitives used by :class:`mxnet_tpu.kvstore_dist.KVStoreTPU`:

- :func:`initialize` — join the process group.  Reads the ``MXTPU_*`` envs
  planted by ``tools/launch.py`` (the launcher analog), so worker scripts
  run unmodified single- or multi-process, exactly as reference scripts
  only consult ``DMLC_ROLE``/``DMLC_PS_ROOT_URI`` when present.
- :class:`Collective` — a one-axis global mesh over one designated device
  per process, with jitted AllReduce/Broadcast lowered by GSPMD to real
  XLA collectives (``kvstore_dist.h:190-240``'s wire-level reduction,
  minus the wire).

On CPU (tests / the virtual-cluster path) the collectives ride Gloo; on
TPU pods they ride ICI/DCN.  Either way the graph is the same jitted HLO.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError, get_env, register_env

__all__ = ["initialize", "is_initialized", "rank", "num_workers",
           "Collective", "barrier", "agree_flag"]

_INITIALIZED = False

ENV_COORDINATOR = register_env(
    "MXTPU_COORDINATOR", scope="tools",
    doc="host:port of the jax.distributed coordinator (set by "
        "tools/launch.py)")
ENV_NUM_WORKERS = register_env(
    "MXTPU_NUM_WORKERS", scope="tools", doc="Process count")
ENV_RANK = register_env(
    "MXTPU_WORKER_RANK", scope="tools", doc="This process's rank")
ENV_PLATFORM = register_env(
    "MXTPU_PLATFORM", scope="tools",
    doc="Force a JAX platform in workers (cpu for the virtual cluster)")


def is_initialized():
    return _INITIALIZED


def _check_backend_untouched():
    """Joining after the first JAX backend touch is unrecoverable user
    error, never retryable — checked once, before the retry ladder."""
    from jax._src import xla_bridge
    if xla_bridge.backends_are_initialized():
        raise MXNetError(
            "distributed.initialize must run before the first JAX backend "
            "touch (importing mxnet_tpu under tools/launch.py does it "
            "automatically; if you initialize manually, do it before "
            "creating any NDArray)")


def _join(coordinator_address, num_processes, process_id, timeout):
    """One attempt to join the coordination service (separated so the
    retry ladder — and tests — can wrap exactly the flaky part)."""
    import jax
    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = float(timeout)
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)
    except Exception:
        # leave no half-joined client behind so the next attempt starts
        # from a clean slate
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — nothing was brought up
            pass
        raise


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               platform=None):
    """Join (or create) the process group.

    Arguments default to the ``MXTPU_*`` envs set by ``tools/launch.py``.
    Single-process (no env, no args) is a no-op so every code path works
    unlaunched.  Must run before the first JAX backend touch — like the
    reference, where ``DMLC_*`` envs must be set before ``kv.create``
    spawns the ps-lite van (``kvstore_server.py:58-68``).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    if coordinator_address is None:
        # implicit env-driven auto-init: spawned helper processes
        # (data-pipeline decode workers) inherit the launcher's MXTPU_*
        # envs but must never join the process group.  Explicit-argument
        # calls (user-managed multiprocessing ranks) are honored anywhere.
        import multiprocessing
        if multiprocessing.current_process().name != "MainProcess":
            return
    coordinator_address = coordinator_address or get_env(ENV_COORDINATOR)
    if num_processes is None:
        num_processes = int(get_env(ENV_NUM_WORKERS, "0") or 0)
    if process_id is None:
        process_id = int(get_env(ENV_RANK, "-1") or -1)
    platform = platform or get_env(ENV_PLATFORM)
    if not coordinator_address or num_processes <= 1:
        return  # single-process; nothing to join
    if process_id < 0:
        raise MXNetError(
            "distributed.initialize: %s is set but %s is not — launch with "
            "tools/launch.py or pass process_id" % (ENV_COORDINATOR, ENV_RANK))
    import jax
    _check_backend_untouched()
    if platform:
        # The TPU plugin platform wins over the JAX_PLATFORMS env var, so
        # the override must go through jax.config (see tests/conftest.py).
        jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        # Cross-process XLA collectives on the CPU backend need an explicit
        # collectives implementation; TPU has ICI natively.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # Preemption makes bring-up flaky by design: the coordinator (rank 0)
    # may still be rescheduling while peers come up, so one attempt is a
    # coin flip on pods.  Retry with backoff, bounded by MXTPU_INIT_RETRIES
    # / MXTPU_INIT_TIMEOUT (per-attempt coordination-service timeout),
    # logging every attempt — the elastic-bring-up discipline the ps-lite
    # tracker got from its own van retries.
    from .resilience import retry, ENV_INIT_RETRIES, ENV_INIT_TIMEOUT, \
        ENV_INIT_BACKOFF
    attempts = int(get_env(ENV_INIT_RETRIES, "3"))
    timeout = get_env(ENV_INIT_TIMEOUT)
    backoff = float(get_env(ENV_INIT_BACKOFF, "1.0"))
    retry(lambda: _join(coordinator_address, num_processes, process_id,
                        timeout),
          attempts=attempts, backoff=backoff,
          retry_on=(RuntimeError, ConnectionError, TimeoutError, MXNetError),
          name="distributed.initialize[rank %d]" % process_id)
    _INITIALIZED = True


def rank():
    import jax
    return jax.process_index()


def num_workers():
    import jax
    return jax.process_count()


def barrier(tag="mxtpu_barrier"):
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def agree_flag(flag):
    """Cross-process OR of a local boolean — the preemption-consensus
    primitive.  The scheduler's SIGTERM lands on different ranks at
    different instants; if each rank consumed its own flag, one rank
    would enter the (collective) checkpoint gather while another entered
    the next step's allreduce and the job would deadlock inside its
    grace window.  Agreeing at every step boundary makes all ranks take
    the same branch at the same boundary: any rank signaled => every
    rank checkpoints.  Single-process returns the flag unchanged; the
    multi-process cost is one scalar allgather per call."""
    import jax
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils
    total = multihost_utils.process_allgather(np.int32(bool(flag)))
    return bool(np.asarray(total).sum() > 0)


class Collective:
    """Jitted cross-process collectives over a 1-axis global device mesh.

    One designated device per process forms a ``("worker",)`` mesh; a value
    contributed by each process becomes one shard of a global
    ``(num_workers, *shape)`` array, and a jitted reduction with replicated
    ``out_shardings`` makes GSPMD emit a device-side AllReduce.  This is
    the reference's push-side tree reduction (``comm.h:120-179``) and
    server aggregation (``kvstore_dist_server.h``) collapsed into one XLA
    collective — no host staging, no O(num_workers) host memory.
    """

    def __init__(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        self._jax = jax
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        self._devices = [per_proc[i] for i in sorted(per_proc)]
        self.num_workers = len(self._devices)
        self.rank = jax.process_index()
        self._local = per_proc[self.rank]
        self._mesh = Mesh(np.asarray(self._devices), ("worker",))
        self._in_sharding = NamedSharding(self._mesh, PartitionSpec("worker"))
        self._rep_sharding = NamedSharding(self._mesh, PartitionSpec())
        self._sum = jax.jit(lambda x: x.sum(axis=0),
                            out_shardings=self._rep_sharding)

    def _global(self, x):
        """Lay out this process's contribution as one mesh shard."""
        jnp = self._jax.numpy
        local = self._jax.device_put(jnp.asarray(x), self._local)
        local = local.reshape((1,) + local.shape)
        return self._jax.make_array_from_single_device_arrays(
            (self.num_workers,) + tuple(x.shape), self._in_sharding, [local])

    def _local_view(self, out):
        """The replicated result's addressable copy on this process."""
        return out.addressable_shards[0].data

    @staticmethod
    def _fault_point():
        """Deterministic fault points shared by every collective entry:
        "collective" raises (a peer dropped: the all-or-nothing failure
        every rank sees), "hang_collective" stalls the caller (a wedged
        reduction — the hung-step watchdog's production target, made
        reproducible on the CPU tier)."""
        from .resilience import faults
        faults.maybe_hang("hang_collective")
        faults.maybe_fail(
            "collective", "injected collective failure (a peer is gone; "
            "relaunch and resume)")

    def allreduce_sum(self, x):
        """Sum a same-shaped array across all worker processes."""
        self._fault_point()
        if self.num_workers == 1:
            return x
        return self._local_view(self._sum(self._global(x)))

    def broadcast(self, x, root=0):
        """Every process receives root's value (shape/dtype must agree).

        Lowered as mask-and-AllReduce: exact, since ``x*1 + 0*y == x``.
        The analog of init-time weight broadcast from worker 0's push
        (``kvstore_dist.h`` Init + pull).
        """
        self._fault_point()
        if self.num_workers == 1:
            return x
        contrib = x if self.rank == root else np.zeros_like(x)
        return self._local_view(self._sum(self._global(contrib)))


# ---------------------------------------------------------------------------
# Liveness heartbeats (the reference's ps-lite heartbeat machinery behind
# KVStore::get_num_dead_node, kvstore_dist.h:158-167).  Each process
# periodically stamps a key in the JAX coordination service's key-value
# store; any process can then count peers whose stamp has gone stale.
# Collectives themselves remain all-or-nothing (a dead rank fails the next
# collective on every rank) — heartbeats exist so monitoring/driver code
# can OBSERVE which rank died, like the reference's dead-node query.
# ---------------------------------------------------------------------------

_HB_PREFIX = "mxtpu_hb/"
_HB_THREAD = None
_HB_STOP = None
HEARTBEAT_INTERVAL = 2.0


def _kv_client():
    if not _INITIALIZED:
        return None
    from jax._src import distributed as _jd
    return _jd.global_state.client


def start_heartbeat(interval=None):
    """Begin stamping this process's liveness key (idempotent).  Runs on a
    daemon thread; dist kvstores start it automatically."""
    global _HB_THREAD, _HB_STOP
    client = _kv_client()
    if client is None or _HB_THREAD is not None:
        return False
    import threading
    import time as _time

    interval = float(interval or HEARTBEAT_INTERVAL)
    key = _HB_PREFIX + str(rank())
    stop = threading.Event()

    def beat():
        while not stop.is_set():
            try:
                client.key_value_set(key, repr(_time.time()),
                                     allow_overwrite=True)
            except Exception:  # noqa: BLE001 — coordinator gone: job is over
                return
            stop.wait(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name="mxtpu-heartbeat")
    t.start()
    _HB_THREAD, _HB_STOP = t, stop
    import atexit
    atexit.register(stop.set)
    return True


# Observer-side liveness cache: rank -> (last stamp value seen, local
# monotonic time it changed, provisional).  Ages are measured with the
# *observer's* clock from the moment the stamp last changed — never by
# differencing a remote wall clock against ours, so NTP steps /
# cross-host skew cannot fake a dead (or alive) worker.  Same discipline
# as ps-lite, which uses the receiver's own timestamps for heartbeat
# staleness.  ``provisional`` marks stamps we have only seen once: the
# observer cannot tell a fresh stamp from a dead worker's last words, so
# such entries report age None (unknown) rather than 0 (alive) until the
# stamp is seen to change.
_HB_OBSERVED = {}
_HB_CLIENT = None  # client identity the cache was built against


def _hb_observed(client):
    """The liveness cache, cleared whenever the coordination client is a
    different object than last time (re-initialised KV client means every
    cached observation time is meaningless)."""
    global _HB_CLIENT
    if client is not _HB_CLIENT:
        _HB_OBSERVED.clear()
        _HB_CLIENT = client
    return _HB_OBSERVED


#: non-blocking KV read surfaces across jax builds, best first: some
#: DistributedRuntimeClient builds expose ``key_value_try_get``, others
#: only a prefix scan (``key_value_dir_get``) or the blocking get.  The
#: heartbeat OBSERVER must work on all of them — on a build where no
#: surface exists, liveness reads honestly report "unknown" and
#: ``heartbeat_supported()`` lets callers (tests/dist drills) probe for
#: the capability instead of mis-reading dead=0 forever.
def _hb_stamps(client):
    """rank -> raw stamp for every rank currently published, or None
    when this client exposes no usable read surface."""
    if hasattr(client, "key_value_try_get"):
        out = {}
        for r in range(num_workers()):
            try:
                out[r] = client.key_value_try_get(_HB_PREFIX + str(r))
            except Exception:  # noqa: BLE001 — not yet written
                pass
        return out
    if hasattr(client, "key_value_dir_get"):
        out = {}
        try:
            items = client.key_value_dir_get(_HB_PREFIX)
        except Exception:  # noqa: BLE001 — nothing published yet
            return out
        for key, value in items:
            tail = str(key).rsplit("/", 1)[-1]
            if tail.isdigit():
                out[int(tail)] = value
        return out
    if hasattr(client, "blocking_key_value_get"):
        out = {}
        for r in range(num_workers()):
            try:
                out[r] = client.blocking_key_value_get(
                    _HB_PREFIX + str(r), 50)
            except Exception:  # noqa: BLE001 — missing key times out
                pass
        return out
    return None


def heartbeat_supported():
    """True when this process can both publish and OBSERVE heartbeats
    (jax builds vary in which coordinator-KV read methods the client
    exposes; without any, ``num_dead_nodes`` can never see a stale
    stamp).  False outside a joined process group."""
    client = _kv_client()
    if client is None:
        return False
    return hasattr(client, "key_value_set") and any(
        hasattr(client, m) for m in
        ("key_value_try_get", "key_value_dir_get",
         "blocking_key_value_get"))


def heartbeat_ages():
    """rank -> seconds since its heartbeat value was last seen to change,
    measured on the local monotonic clock.  None = unknown: either never
    written, or written but not yet observed to change (a stamp seen only
    once could equally be a live worker's latest beat or a dead worker's
    last — see num_dead_nodes for how frozen stamps age out)."""
    import time as _time
    client = _kv_client()
    if client is None:
        return {}
    obs = _hb_observed(client)
    now = _time.monotonic()
    stamps = _hb_stamps(client)
    if stamps is None:
        return {r: None for r in range(num_workers())}
    ages = {}
    for r in range(num_workers()):
        if r not in stamps:
            ages[r] = None
            continue
        stamp = stamps[r]
        prev = obs.get(r)
        if prev is None:
            obs[r] = (stamp, now, True)
        elif prev[0] != stamp:
            obs[r] = (stamp, now, False)
        rec = obs[r]
        ages[r] = None if rec[2] else now - rec[1]
    return ages


def num_dead_nodes(node_id=-1, timeout=60):
    """Count workers whose heartbeat is older than ``timeout`` seconds
    (reference get_num_dead_node semantics; node_id filtering reduces to
    "any worker" here — there are no separate server/scheduler roles).
    Workers that never heartbeat (pre-start) are not counted dead; a
    worker whose stamp has stayed frozen for the whole of a > timeout
    observation window is (its beat thread would have re-stamped)."""
    import time as _time
    ages = heartbeat_ages()
    now = _time.monotonic()
    dead = 0
    for r, age in ages.items():
        if age is not None and age > timeout:
            dead += 1
            continue
        rec = _HB_OBSERVED.get(r)
        if (age is None and rec is not None and rec[2]
                and now - rec[1] > timeout):
            dead += 1
    return dead
