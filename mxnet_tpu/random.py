"""Random state management.

The reference seeds per-device mshadow Random resources via
``MXRandomSeed`` (src/resource.cc SeedRandom, python/mxnet/random.py).
TPU-native design: one functional PRNG key chain (jax.random) that the
imperative layer splits from; graph executors fold a per-step counter into
their own key so compiled training steps stay pure.
"""
from __future__ import annotations

import threading

import jax
import numpy as _np

__all__ = ["seed", "next_key", "get_state", "set_state", "uniform",
           "normal", "randint"]

_state = threading.local()
_DEFAULT_SEED = 0


def _get_key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


def seed(seed_state):
    """Seed the global RNG (parity: python/mxnet/random.py mx.random.seed).

    Also reseeds numpy-free framework components; numpy's own RNG is NOT
    touched (same behavior as the reference, which warns about this in
    random.py docstring).
    """
    if not isinstance(seed_state, (int, _np.integer)):
        raise ValueError("seed must be an int")
    global _seed_int
    _state.key = jax.random.PRNGKey(int(seed_state))
    _seed_int = int(seed_state)


_seed_int = _DEFAULT_SEED


def get_seed():
    """The integer last passed to :func:`seed` (framework default if never
    seeded) — lets host-side components (data-augmentation workers) derive
    deterministic streams from the same user seed.  Process-global (not
    thread-local, unlike the PRNG key chain): it is host metadata, and an
    iterator built on a loader thread must see the main thread's seed."""
    return _seed_int


def next_key():
    """Split and return a fresh PRNG key."""
    key = _get_key()
    _state.key, sub = jax.random.split(key)
    return sub


def get_state():
    """This thread's RNG chain as a JSON-serializable dict — saved into
    mid-epoch (preemption) checkpoints so a resumed run's stochastic
    layers draw the exact keys the interrupted run would have."""
    return {"key": _np.asarray(_get_key()).tolist(), "seed": _seed_int}


def set_state(state):
    """Restore a :func:`get_state` snapshot (the mid-epoch-resume
    counterpart of :func:`seed`)."""
    global _seed_int
    key = _np.asarray(state["key"], dtype=_np.uint32)
    _state.key = jax.numpy.asarray(key)
    _seed_int = int(state.get("seed", _DEFAULT_SEED))


def peek_key():
    """A key derived from the current state WITHOUT advancing it — for
    side-channel inspection (e.g. a metrics-only forward) that must not
    shift the training trajectory's random stream."""
    return jax.random.fold_in(_get_key(), 0x9e3779b9)


def uniform(low=0, high=1, shape=(), ctx=None, dtype="float32", out=None):
    from . import ndarray as nd
    return nd.uniform(low=low, high=high, shape=shape, ctx=ctx, dtype=dtype, out=out)


def normal(loc=0, scale=1, shape=(), ctx=None, dtype="float32", out=None):
    from . import ndarray as nd
    return nd.normal(loc=loc, scale=scale, shape=shape, ctx=ctx, dtype=dtype, out=out)


def randint(low, high, shape=(), ctx=None, dtype="int32"):
    from . import ndarray as nd
    data = jax.random.randint(next_key(), shape, low, high)
    return nd.NDArray._from_jax(data.astype(dtype), ctx)
