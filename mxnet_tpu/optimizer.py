"""Optimizers (reference python/mxnet/optimizer.py, 702 LoC).

The update rules are the registered optimizer ops (ops/tensor.py
sgd_update/adam_update/... — the same ops the reference's dist server runs,
src/operator/tensor/optimizer_op.cc:18-73), so the Python Optimizer classes
here are thin state machines over jit-compiled updates; inside a fused
training step (kvstore 'tpu') the identical rules run in-graph.
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray, zeros
from . import ndarray as nd

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "SGLD", "DCASGD", "Test", "Updater", "get_updater",
           "create", "register"]

opt_registry = Registry("optimizer")
register = opt_registry.register


class Optimizer(object):
    """Base optimizer (reference optimizer.py:Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        # unconditional: the bias/gamma wd exclusion must apply even without
        # a symbol (reference optimizer.py also seeds wd_mult from idx2name)
        self.set_lr_mult({})
        self.set_wd_mult({})

    @staticmethod
    def create_optimizer(name, **kwargs):
        return opt_registry.create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "lr_mult" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["lr_mult"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # the reference skips weight decay for biases/gammas/betas by
            # name pattern (optimizer.py set_wd_mult)
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "wd_mult" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["wd_mult"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


def create(name, **kwargs):
    return opt_registry.create(name, **kwargs)


@register(aliases=("ccsgd",))
class SGD(Optimizer):
    """SGD with momentum (reference optimizer.py:279; update rule =
    sgd_update / sgd_mom_update ops)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient if self.clip_gradient
                      is not None else -1.0)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, out=weight, **kwargs)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py:380)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        if state is not None:
            state *= self.momentum
            grad += wd * weight
            state += grad
            grad += self.momentum * state
            weight -= lr * grad
        else:
            weight -= lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:416)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        noise = nd.normal(loc=0, scale=math.sqrt(lr), shape=weight.shape,
                          ctx=weight.context)
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:451; update = adam_update op with the
    reference's bias-corrected effective lr)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=[weight, mean, var],
                       lr=lr, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, wd=wd,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient if self.clip_gradient
                       is not None else -1.0)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:499)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        state += grad * grad
        weight -= lr * (grad / nd.sqrt(state + self.float_stable_eps)
                        + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp (reference optimizer.py:536; centered=True uses Graves'
    variant = rmspropalex_update, else rmsprop_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, ctx=weight.context),
                    zeros(weight.shape, ctx=weight.context),
                    zeros(weight.shape, ctx=weight.context))
        return (zeros(weight.shape, ctx=weight.context),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      gamma1=self.gamma1, epsilon=self.epsilon,
                      clip_gradient=self.clip_gradient if self.clip_gradient
                      is not None else -1.0,
                      clip_weights=self.clip_weights if self.clip_weights
                      is not None else -1.0)
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, out=[weight, n], **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  out=[weight, n, g, delta],
                                  gamma2=self.gamma2, **kwargs)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:605)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context),
                zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1.0 - self.rho) * grad * grad
        current_delta = nd.sqrt(acc_delta + self.epsilon) / \
            nd.sqrt(acc_g + self.epsilon) * grad
        acc_delta[:] = self.rho * acc_delta + \
            (1.0 - self.rho) * current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py:325)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, ctx=weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        mom, previous_weight = state
        if mom is not None:
            mom[:] = self.momentum * mom - lr * (
                grad + wd * weight +
                self.lamda * grad * grad * (weight - previous_weight))
            weight += mom
        else:
            weight += -lr * (grad + wd * weight + self.lamda * grad * grad *
                             (weight - previous_weight))
        previous_weight[:] = weight


@register
class Test(Optimizer):
    """Test optimizer: w -= rescale_grad * g (reference optimizer.py:653)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


class Updater(object):
    """Stateful per-key updater closure used by KVStore (reference
    optimizer.py:669 get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        loaded = pickle.loads(states)
        if isinstance(loaded, dict) and "states" in loaded \
                and "num_update" in loaded:
            # blob saved by the fused SPMD path ({name: tuple}) — convert to
            # this updater's {index_or_name: state} convention.  With
            # multiple contexts idx2name maps SEVERAL indices (i*len(ctx)+k)
            # to one name, and every per-device slot must get the restored
            # state, not just one.
            name2indices = {}
            for i, n in (getattr(self.optimizer, "idx2name", {}) or {}).items():
                name2indices.setdefault(n, []).append(i)
            self.optimizer.num_update = max(self.optimizer.num_update,
                                            loaded["num_update"])
            converted = {}
            for name, s in loaded["states"].items():
                if len(s) == 0:
                    val = None
                elif len(s) == 1:
                    val = s[0]
                else:
                    val = tuple(s)
                for key in name2indices.get(name, [name]):
                    converted[key] = val
            loaded = converted
        self.states = {k: _state_from_numpy(v) for k, v in loaded.items()}

    def get_states(self):
        serializable = {}
        for k, v in self.states.items():
            serializable[k] = _state_to_numpy(v)
        return pickle.dumps(serializable)


def _state_to_numpy(v):
    if isinstance(v, NDArray):
        return v.asnumpy()
    if isinstance(v, (tuple, list)):
        return tuple(_state_to_numpy(x) for x in v)
    return v


def _state_from_numpy(v):
    if isinstance(v, np.ndarray):
        from .ndarray import array as nd_array
        return nd_array(v, dtype=v.dtype)
    if isinstance(v, (tuple, list)):
        return tuple(_state_from_numpy(x) for x in v)
    return v


def get_updater(optimizer):
    return Updater(optimizer)
