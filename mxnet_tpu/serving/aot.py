"""AOT executable store: serialized COMPILED (model, bucket) forwards.

The persistent compile cache (``MXTPU_COMPILE_CACHE``) removes XLA
compilation from a replica's bring-up — but the dominant remaining cost
on re-trace is Python: binding the symbol graph and tracing one jitted
forward per bucket shape (seconds for a deep net, per process).  This
store removes THAT too: the fleet's warmup builder compiles each
(model, bucket) forward ONCE, serializes the compiled executable
(``jax.experimental.serialize_executable`` — the true AOT artifact:
no trace, no lower, no compile at load), and a fresh or respawned
replica ``deserialize_and_load``\\ s it in ~0.1s per program.
``bench.py fleet`` measures the effect as ``fleet_warm_start_x``.

Artifacts are WEIGHT-FREE: the compiled program takes the parameters as
call arguments (the pool keeps the single device-resident copy), so a
store is a few hundred KB per program regardless of model size, and
reloading never duplicates weights.

Store layout (``<MXTPU_COMPILE_CACHE>/aot/``)::

    <model>.json            meta: sample shapes, dtype, param/aux names,
                            platform, buckets — verified before loading
    <model>-b<bucket>.exec  the serialized executable
    <model>-b<bucket>.tree  its pickled (in_tree, out_tree)

A meta mismatch (different shapes/dtype/platform/param set) or a
deserialization failure falls back to the classic trace-and-compile
warmup with a warning — the store can go stale, serving must not.
Trust model: the store directory is operator-owned exactly like a
checkpoint directory (the ``.tree`` files are pickles, as checkpoint
state already is).

Executables are platform-specific by nature: a store built under the
replica device env (``fleet warmup`` builds under replica 0's) loads on
every replica of that fleet; it will refuse (and fall back) anywhere
else.  Bit-exactness: every replica of a fleet loads the SAME compiled
bytes, so the (bucket-shape) bit-stability contract holds fleet-wide by
construction — stronger than N independent compiles.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np

from ..base import MXNetError

__all__ = ["AotStore", "aot_dir_for_cache"]

_META_VERSION = 1


def aot_dir_for_cache(cache_dir):
    """The store's location inside a compile-cache directory."""
    return os.path.join(cache_dir, "aot")


def _log():
    import logging
    return logging.getLogger(__name__)


def dev_array(v):
    """NDArray -> its device buffer; anything else -> jnp.asarray.
    The ONE unwrap rule every serving forward builder shares."""
    import jax.numpy as jnp
    from ..ndarray import NDArray
    return v._data if isinstance(v, NDArray) else jnp.asarray(v)


def eval_closure(eval_fn, fills, aux_fills, input_names):
    """The shared body of every serving forward: merge params +
    zero-filled args, zero-filled missing aux, run the eval graph in
    inference mode with the fixed PRNG convention.  ``run(params_dict,
    aux_dict, inputs_tuple) -> tuple(outputs)``.  Lives in ONE place so
    the int8 path and the AOT exporter cannot drift on the rng/train
    flag or the fill dtype."""
    import jax
    import jax.numpy as jnp

    def run(params, auxs, inputs):
        merged = dict(params)
        merged.update({n: jnp.zeros(s, jnp.float32)
                       for n, s in fills.items()})
        merged.update(dict(zip(input_names, inputs)))
        full_aux = dict(auxs)
        full_aux.update({n: jnp.zeros(s, jnp.float32)
                         for n, s in aux_fills.items()})
        outs, _ = eval_fn(merged, full_aux, jax.random.PRNGKey(0),
                          False)
        return tuple(outs)

    return run


def graph_fills(symbol, shapes, known_args, known_auxs):
    """The Predictor.reshape allocation rule, shared by every serving
    forward builder (Predictor itself, the int8 path, the AOT export):
    args absent from the blob AND the inputs (loss labels at
    inference) and missing aux states are zero-filled at their
    inferred shapes.  Returns ``(fills, aux_fills)`` as
    ``{name: shape}`` dicts.  Lives in ONE place so the int8 and AOT
    forwards can never drift from each other on what gets filled."""
    arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
    known = set(known_args) | set(shapes)
    fills = {n: tuple(s)
             for n, s in zip(symbol.list_arguments(), arg_shapes)
             if n not in known}
    aux_fills = {n: tuple(s)
                 for n, s in zip(symbol.list_auxiliary_states(),
                                 aux_shapes)
                 if n not in known_auxs}
    return fills, aux_fills


class AotStore(object):
    """One directory of serialized compiled forwards."""

    def __init__(self, directory):
        self.dir = directory

    def _base(self, model, bucket):
        return os.path.join(self.dir, "%s-b%d" % (model, int(bucket)))

    def _meta_path(self, model):
        return os.path.join(self.dir, "%s.json" % model)

    @staticmethod
    def _platform():
        import jax
        return jax.default_backend()

    def meta(self, model):
        try:
            with open(self._meta_path(model)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def buckets(self, model):
        doc = self.meta(model)
        return sorted(int(b) for b in (doc or {}).get("buckets", []))

    # -- build side --------------------------------------------------------
    def save(self, model, bucket, compiled, meta):
        """Serialize one compiled executable + record/refresh the
        model's meta doc (``meta``: sample_shapes/dtype/param_names/
        aux_names/input_names)."""
        from jax.experimental import serialize_executable as se
        from ..resilience import atomic_write
        os.makedirs(self.dir, exist_ok=True)
        payload, in_tree, out_tree = se.serialize(compiled)
        base = self._base(model, bucket)
        atomic_write(base + ".exec", bytes(payload))
        atomic_write(base + ".tree",
                     pickle.dumps((in_tree, out_tree), protocol=4))
        doc = self.meta(model) or {}
        doc.update(meta)
        doc["meta_version"] = _META_VERSION
        doc["platform"] = self._platform()
        buckets = set(int(b) for b in doc.get("buckets", []))
        buckets.add(int(bucket))
        doc["buckets"] = sorted(buckets)
        atomic_write(self._meta_path(model),
                     json.dumps(doc, indent=2, sort_keys=True))
        return base

    # -- load side ---------------------------------------------------------
    def verify(self, model, meta):
        """Does the store's meta match this pool entry?  Returns the
        meta doc on match, None (with a warning) otherwise — stale
        artifacts must fall back, never serve wrong math."""
        doc = self.meta(model)
        if doc is None:
            return None
        checks = dict(meta)
        checks["platform"] = self._platform()
        checks["meta_version"] = _META_VERSION
        for key, want in checks.items():
            got = doc.get(key)
            # JSON roundtrips tuples as lists
            norm = lambda v: json.loads(json.dumps(v))  # noqa: E731
            if norm(got) != norm(want):
                _log().warning(
                    "AOT store %s: meta mismatch for %r on %r "
                    "(store %r != pool %r) — falling back to "
                    "trace warmup", self.dir, model, key, got, want)
                return None
        return doc

    def load(self, model, bucket):
        """One executable -> callable, or None (missing/corrupt —
        caller falls back)."""
        from jax.experimental import serialize_executable as se
        base = self._base(model, bucket)
        try:
            with open(base + ".exec", "rb") as f:
                payload = f.read()
            with open(base + ".tree", "rb") as f:
                in_tree, out_tree = pickle.load(f)
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — stale/foreign artifact
            _log().warning("AOT store %s: cannot load %s-b%d (%s: %s) — "
                           "falling back to trace warmup",
                           self.dir, model, bucket, type(e).__name__, e)
            return None


def build_forward(symbol, arg_params, aux_params, sample_shapes,
                  bucket):
    """The canonical AOT forward for one bucket: a compiled function of
    ``(param_list, aux_list, *inputs)`` (params in sorted-name order —
    weight-free executable, the pool passes its device-resident copy).
    Returns ``(compiled, input_names)``.  The math is the same
    ``executor._build_eval`` program the Predictor runs — the bit-parity
    tests pin the two paths against each other."""
    import jax
    import jax.numpy as jnp
    from ..executor import _build_eval

    eval_fn = _build_eval(symbol)
    pnames = sorted(arg_params)
    anames = sorted(aux_params)
    pv = [dev_array(arg_params[n]) for n in pnames]
    av = [dev_array(aux_params[n]) for n in anames]
    input_names = sorted(sample_shapes)
    shapes = {k: (int(bucket),) + tuple(s)
              for k, s in sample_shapes.items()}
    fills, aux_fills = graph_fills(symbol, shapes, arg_params,
                                   aux_params)
    run = eval_closure(eval_fn, fills, aux_fills, input_names)

    def infer(params, auxv, *inputs):
        return run(dict(zip(pnames, params)),
                   dict(zip(anames, auxv)), inputs)

    xs = [jax.ShapeDtypeStruct(shapes[n], jnp.float32)
          for n in input_names]
    compiled = jax.jit(infer).lower(pv, av, *xs).compile()
    return compiled, (pv, av)


def entry_meta(entry):
    """The verification meta for one pool entry (shape/dtype/param-set
    identity — what must match for a stored executable to be THIS
    model's forward)."""
    if entry.sample_shapes is None:
        raise MXNetError("model %r has no declared sample_shapes — the "
                         "AOT store needs them" % entry.name)
    return {"sample_shapes": {k: list(v)
                              for k, v in sorted(
                                  entry.sample_shapes.items())},
            "dtype": entry.dtype or "float32",
            "param_names": sorted(entry.arg_params),
            "aux_names": sorted(entry.aux_params),
            "param_digest": params_digest(entry.arg_params,
                                          entry.aux_params)}


def params_digest(arg_params, aux_params):
    """Cheap shape/dtype digest of the parameter set (NOT a content
    hash — weights ride at call time, only the program signature must
    match)."""
    import hashlib
    h = hashlib.sha256()
    for prefix, d in (("arg", arg_params), ("aux", aux_params)):
        for k in sorted(d):
            v = d[k]
            h.update(("%s:%s:%s:%s;" % (
                prefix, k, tuple(getattr(v, "shape", ())),
                np.dtype(getattr(v, "dtype", np.float32)).name))
                .encode())
    return h.hexdigest()[:16]
