"""Admission-control HTTP front end: ``/predict/<model>``, ``/healthz``,
``/stats`` — bounded queues, load shedding, graceful SIGTERM drain.

Admission (Clipper-style SLO-aware control): a request is REFUSED with
429 before it ever queues when the model's queue depth is at
``MXTPU_SERVE_MAX_QUEUE`` (``shed_queue``) or the estimated queue wait
exceeds the ``MXTPU_SERVE_SLO_MS`` latency objective (``shed_slo``) —
under overload a serving system must answer *some* requests inside the
SLO rather than all of them late.  Shed counters and per-stage metrics
(queue depth, batch fill ratio, p50/p99 latency) are live on ``/stats``.

Shutdown composes with ``tools/supervise.py``: SIGTERM flips the daemon
to draining (new predicts get 503, ``/healthz`` reports ``draining``),
every ACCEPTED request finishes and gets its 200, then the process
exits 0.  A wedged forward is the StepWatchdog's job — armed around
each batch dispatch, it dumps stacks and aborts with exit 87 so the
supervisor relaunches the daemon (warm via ``MXTPU_COMPILE_CACHE``).
"""
from __future__ import annotations

import json
import signal
import threading
import time
import uuid
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..base import MXNetError, get_env, register_env
from ..resilience import faults
from .batcher import (BucketBatcher, DeadlineExpired, Draining, QueueFull,
                      TenantQuotaExceeded, parse_buckets)

__all__ = ["ServingFrontend", "ServeClient", "Stats",
           "ENV_SERVE_MAX_QUEUE", "ENV_SERVE_SLO_MS",
           "ENV_SERVE_DEDUP_CAP", "ENV_SERVE_DEDUP_TTL_S"]

ENV_SERVE_MAX_QUEUE = register_env(
    "MXTPU_SERVE_MAX_QUEUE", default=256,
    doc="Per-model queue-depth bound; requests beyond it are shed with "
        "HTTP 429 (`shed_queue` on /stats)")
ENV_SERVE_SLO_MS = register_env(
    "MXTPU_SERVE_SLO_MS", default=0.0,
    doc="Latency SLO: shed (429, `shed_slo`) when the estimated queue "
        "wait exceeds this many ms; 0 disables the estimator")
ENV_SERVE_DEDUP_CAP = register_env(
    "MXTPU_SERVE_DEDUP_CAP", default=1024,
    doc="Idempotency dedup cache: completed 200 responses kept per "
        "daemon for request-id replay (exactly-once serving); the "
        "oldest entry is evicted past the cap (`dedup_evicted_size`); "
        "0 disables replay caching (in-flight dedup still applies)")
ENV_SERVE_DEDUP_TTL_S = register_env(
    "MXTPU_SERVE_DEDUP_TTL_S", default=30.0,
    doc="Idempotency dedup cache entry lifetime: a cached response "
        "older than this is dropped (`dedup_evicted_ttl`) — bounds how "
        "long a request id stays replayable")

#: fault point: armable per-request latency injection in the replica
#: front end — the deterministic stand-in for a gray-failing (slow but
#: alive) replica.  ``arm_hang`` sets the delay; plain ``MXTPU_FAULTS``
#: env arming delays each armed hit by SLOW_REPLICA_DEFAULT_S.
SLOW_REPLICA_FAULT = "slow_replica"
SLOW_REPLICA_DEFAULT_S = 0.25


def _percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (no numpy interp —
    the stats path must stay allocation-light)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class Stats(object):
    """Thread-safe serving metrics: monotonically increasing counters, a
    bounded latency window for percentiles, and batch-fill accounting.
    ``record_latency(ms, tenant=...)`` additionally feeds a bounded
    per-tenant window (at most :data:`MAX_TENANTS` distinct tenants —
    past the cap new tenants fold into the shared window only, so a
    tenant-id flood cannot grow the stats dict without bound)."""

    #: distinct tenants tracked with their own latency window
    MAX_TENANTS = 64

    def __init__(self, window=4096):
        self._lock = threading.Lock()
        self._counters = {"accepted": 0, "completed": 0, "errors": 0,
                          "shed_queue": 0, "shed_slo": 0,
                          "shed_deadline": 0, "rejected": 0}
        self._latencies = deque(maxlen=window)
        self._tenant_lat = {}
        self._batches = 0
        self._rows = 0
        self._bucket_rows = 0
        self._batch_time = 0.0

    def inc(self, key, n=1):
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def record_latency(self, ms, tenant=None):
        with self._lock:
            self._latencies.append(float(ms))
            if tenant:
                win = self._tenant_lat.get(tenant)
                if win is None:
                    if len(self._tenant_lat) >= self.MAX_TENANTS:
                        return
                    win = self._tenant_lat[tenant] = deque(maxlen=512)
                win.append(float(ms))

    def record_batch(self, n, bucket, seconds):
        with self._lock:
            self._batches += 1
            self._rows += int(n)
            self._bucket_rows += int(bucket)
            self._batch_time += float(seconds)

    #: samples feeding the RECENT percentile (``p99_recent``): small on
    #: purpose, so a replica that recovers from a slow spell washes the
    #: spell out of its reported tail within ~this many requests (the
    #: gray-failure detector's re-admission signal — a 4096-sample p99
    #: would pin an ejected replica slow for thousands of requests)
    RECENT_WINDOW = 64

    def latency_percentile(self, q, recent=256, min_count=16):
        """Percentile of the last ``recent`` latency samples, or None
        below ``min_count`` samples — the adaptive hedge trigger
        (fleet/router.py) reads this instead of the full window so the
        threshold tracks what latency looks like NOW."""
        with self._lock:
            tail = list(self._latencies)[-int(recent):]
        if len(tail) < int(min_count):
            return None
        return _percentile(sorted(tail), q)

    def snapshot(self):
        with self._lock:
            raw = list(self._latencies)
            counters = dict(self._counters)
            tenant_lat = {t: sorted(w)
                          for t, w in self._tenant_lat.items()}
            batches, rows = self._batches, self._rows
            bucket_rows, batch_time = self._bucket_rows, self._batch_time
        lat = sorted(raw)
        recent = sorted(raw[-self.RECENT_WINDOW:])
        out = {"counters": counters,
               "latency_ms": {"count": len(lat),
                              "p50": _percentile(lat, 50),
                              "p99": _percentile(lat, 99),
                              "p99_recent": _percentile(recent, 99)},
               "batches": {"count": batches, "rows": rows,
                           "fill_ratio": round(rows / bucket_rows, 4)
                           if bucket_rows else None,
                           "avg_ms": round(batch_time / batches * 1000.0, 3)
                           if batches else None}}
        if tenant_lat:
            out["tenant_latency_ms"] = {
                t: {"count": len(w), "p50": _percentile(w, 50),
                    "p99": _percentile(w, 99)}
                for t, w in tenant_lat.items()}
        return out

    # -- multi-process merge (the sharded fleet front end) -----------------
    def export(self, window_cap=1024):
        """Serializable raw state for cross-process merging: counters,
        the latency window tail, batch accounting.  What each router
        worker dumps; :meth:`merged_snapshot` recombines."""
        with self._lock:
            return {"counters": dict(self._counters),
                    "window": list(self._latencies)[-int(window_cap):],
                    "batches": [self._batches, self._rows,
                                self._bucket_rows, self._batch_time]}

    @classmethod
    def merged_snapshot(cls, exports):
        """Combine :meth:`export` dicts from N processes into one
        ``snapshot()``-shaped payload: counters summed, percentiles over
        the concatenated windows (each window is a bounded tail, so the
        merged p50/p99 reflects recent traffic across the shard)."""
        counters = {}
        window = []
        batches = rows = bucket_rows = 0
        batch_time = 0.0
        for exp in exports:
            for k, v in (exp.get("counters") or {}).items():
                counters[k] = counters.get(k, 0) + v
            window.extend(exp.get("window") or ())
            b = exp.get("batches") or (0, 0, 0, 0.0)
            batches += int(b[0])
            rows += int(b[1])
            bucket_rows += int(b[2])
            batch_time += float(b[3])
        lat = sorted(window)
        recent = sorted(window[-cls.RECENT_WINDOW:])
        return {"counters": counters,
                "latency_ms": {"count": len(lat),
                               "p50": _percentile(lat, 50),
                               "p99": _percentile(lat, 99),
                               "p99_recent": _percentile(recent, 99)},
                "batches": {"count": batches, "rows": rows,
                            "fill_ratio": round(rows / bucket_rows, 4)
                            if bucket_rows else None,
                            "avg_ms": round(batch_time / batches
                                            * 1000.0, 3)
                            if batches else None},
                "merged_from": len(exports)}


class _Pending(object):
    """One in-flight keyed request: duplicates park on ``event`` and
    read the original's outcome instead of executing again."""

    __slots__ = ("event", "status", "body")

    def __init__(self):
        self.event = threading.Event()
        self.status = None
        self.body = None


class _DedupCache(object):
    """The replica-side half of exactly-once serving: a bounded
    idempotency cache keyed ``(model, tenant, request id)``.

    - a duplicate of a COMPLETED request replays the cached response
      bytes without re-entering the batcher (``dedup_hits``);
    - a duplicate of an IN-FLIGHT request waits on the original's
      completion and shares its one execution (``dedup_joined``);
    - only 200s are cached (a shed/error answer must not mask a later
      retry that would have succeeded), bounded by entry count
      (``dedup_evicted_size``) and TTL (``dedup_evicted_ttl``).

    Correctness does NOT rest on this cache: the batcher's bit-exactness
    contract (serving/batcher.py) makes a cross-replica re-execution of
    the same bytes bit-identical, so a dedup MISS on a retried request
    is still the right answer — the cache removes the double execution,
    not a wrong one."""

    def __init__(self, cap=None, ttl_s=None, stats=None):
        self.cap = int(get_env(ENV_SERVE_DEDUP_CAP)
                       if cap is None else cap)
        self.ttl_s = float(get_env(ENV_SERVE_DEDUP_TTL_S)
                           if ttl_s is None else ttl_s)
        self.stats = stats
        self._lock = threading.Lock()
        self._done = OrderedDict()      # key -> (expires_at, status, body)
        self._inflight = {}             # key -> _Pending

    def _inc(self, key):
        if self.stats is not None:
            self.stats.inc(key)

    def _purge(self, now):
        # lazy TTL sweep from the insertion-order front (uniform TTL:
        # the front is the stalest); claim() re-checks per entry anyway
        while self._done:
            key = next(iter(self._done))
            if self._done[key][0] > now:
                break
            del self._done[key]
            self._inc("dedup_evicted_ttl")

    def claim(self, key):
        """``("replay", (status, body))`` for a completed duplicate,
        ``("join", pending)`` for an in-flight duplicate, or
        ``("run", pending)`` — the caller owns the execution and must
        :meth:`complete` the pending slot."""
        now = time.monotonic()
        with self._lock:
            self._purge(now)
            ent = self._done.get(key)
            if ent is not None:
                if ent[0] <= now:
                    del self._done[key]
                    self._inc("dedup_evicted_ttl")
                else:
                    self._done.move_to_end(key)
                    self._inc("dedup_hits")
                    return "replay", (ent[1], ent[2])
            p = self._inflight.get(key)
            if p is not None:
                self._inc("dedup_joined")
                return "join", p
            p = self._inflight[key] = _Pending()
            return "run", p

    def complete(self, key, pending, status, body):
        """Publish the original's outcome: waiters wake with exactly
        these bytes; a 200 additionally becomes replayable until
        TTL/size eviction."""
        with self._lock:
            self._inflight.pop(key, None)
            if status == 200 and self.cap > 0:
                self._done[key] = (time.monotonic() + self.ttl_s,
                                   status, body)
                self._done.move_to_end(key)
                while len(self._done) > self.cap:
                    self._done.popitem(last=False)
                    self._inc("dedup_evicted_size")
        pending.status, pending.body = status, body
        pending.event.set()

    def export(self):
        with self._lock:
            return {"entries": len(self._done),
                    "inflight": len(self._inflight),
                    "cap": self.cap, "ttl_s": self.ttl_s}


class ServingFrontend(object):
    """The daemon: a :class:`ModelPool` behind per-model batchers and a
    stdlib threading HTTP server.

    HTTP surface::

        POST /predict/<model>   body: {"inputs": {name: nested-list}}
                                 (or {"data": [...]} shorthand, or a raw
                                 .npy body with Content-Type
                                 application/x-npy for the sole input)
        POST /predict_seq/<model>  body: {"tokens": [...]} — one
                                 variable-length token sequence, length-
                                 bucketed + trimmed (serving/sequence.py)
        GET  /healthz           {"status": "ok"|"draining", ...}
        GET  /stats             counters + queue depth + fill + p50/p99

    Responses: 200 result, 400 malformed, 404 unknown model, 429 shed
    (queue bound / SLO), 503 draining.  Accepted work is never answered
    5xx by a drain — that is the SIGTERM contract.
    """

    def __init__(self, pool, host="127.0.0.1", port=0, buckets=None,
                 max_wait_ms=None, max_queue=None, slo_ms=None,
                 watchdog=None, request_timeout=60.0,
                 tenant_weights=None, tenant_quota=None,
                 seq_buckets=None):
        self.pool = pool
        self.host, self.port = host, int(port)
        self.buckets = parse_buckets(buckets)
        #: sequence-LENGTH buckets for /predict_seq (spec string/ints;
        #: None = the MXTPU_SERVE_SEQ_BUCKETS default, parsed lazily so
        #: fixed-shape-only daemons never read the knob)
        self.seq_buckets = seq_buckets
        self._seq_buckets = None
        self.max_wait_ms = max_wait_ms
        #: weighted-fair tenant config, passed to every batcher (None =
        #: the MXTPU_SERVE_TENANT_* env defaults)
        self.tenant_weights = tenant_weights
        self.tenant_quota = tenant_quota
        self.max_queue = int(get_env(ENV_SERVE_MAX_QUEUE)) \
            if max_queue is None else int(max_queue)
        self.slo_ms = float(get_env(ENV_SERVE_SLO_MS)) \
            if slo_ms is None else float(slo_ms)
        #: a StepWatchdog instance (or a zero-arg factory) ENABLING
        #: watchdog coverage.  Each model's batcher gets its OWN
        #: watchdog: armed()'s nesting bookkeeping is single-thread,
        #: and every batcher dispatches on its own thread — one shared
        #: watchdog across models would mis-track overlapping arms (a
        #: wedged forward could go unmonitored, and a depth that never
        #: returns to zero would disarm the watchdog for good)
        self.watchdog = watchdog
        self._watchdogs = []
        self._given_watchdog_used = False
        self.request_timeout = float(request_timeout)
        self.stats = Stats()
        #: the exactly-once layer: request-id dedup for /predict
        self.dedup = _DedupCache(stats=self.stats)
        self.draining = False
        self._batchers = {}
        #: model -> CheckpointWatcher (serving/deploy.py): created by
        #: serve.py --watch or lazily by the /swap admin endpoint
        self.watchers = {}
        self._lock = threading.Lock()
        self._server = None
        self._stopped = threading.Event()

    # -- batching ----------------------------------------------------------
    def _new_watchdog(self):
        """One watchdog per batcher (call with ``_lock`` held).  The
        given instance covers the first model; later models get a fresh
        instance — same class, env-configured budget — or the factory's
        product when ``watchdog`` is callable."""
        if callable(self.watchdog):
            wd = self.watchdog()
        elif not self._given_watchdog_used:
            self._given_watchdog_used = True
            wd = self.watchdog
        else:
            wd = type(self.watchdog)()
        self._watchdogs.append(wd)
        wd.start()
        return wd

    def batcher(self, model, entry=None):
        if entry is None:
            entry = self.pool.get(model)  # raises on unknown model
        with self._lock:
            b = self._batchers.get(model)
            if b is None:
                wd = None if self.watchdog is None else \
                    self._new_watchdog()
                b = BucketBatcher(
                    entry.forward, buckets=self.buckets,
                    max_wait_ms=self.max_wait_ms,
                    max_queue=self.max_queue, name=model,
                    watchdog=wd, stats=self.stats,
                    tenant_weights=self.tenant_weights,
                    tenant_quota=self.tenant_quota)
                self._batchers[model] = b
        return b

    def queue_depths(self):
        with self._lock:
            batchers = dict(self._batchers)
        return {name: b.depth for name, b in batchers.items()}

    # -- continuous deployment (serving/deploy.py) -------------------------
    def watcher(self, model, start=False, **kw):
        """The model's :class:`~.deploy.CheckpointWatcher` (created on
        first use; raises when the model was not loaded from a
        checkpoint directory).  ``start=True`` begins tailing."""
        with self._lock:
            w = self.watchers.get(model)
        if w is None:
            from .deploy import CheckpointWatcher
            w = CheckpointWatcher(self.pool, model, frontend=self, **kw)
            with self._lock:
                w = self.watchers.setdefault(model, w)
        if start:
            w.start()
        return w

    def handle_swap(self, model, epoch=None):
        """The ``POST /swap/<model>`` admin surface: one synchronous
        verify -> stage -> swap -> probe pass (``epoch=None`` promotes
        the newest verified epoch).  Returns ``(status, outcome)`` —
        200 when the model is now serving the requested/newest epoch,
        409 when the promotion was refused (verification, validation or
        probe), 404/503 for unknown model / draining."""
        try:
            self.pool.get(model)
        except MXNetError as e:
            return 404, {"error": str(e), "model": model}
        if self.draining:
            return 503, {"error": "draining", "model": model}
        try:
            w = self.watcher(model)
        except MXNetError as e:   # not a checkpoint-directory model
            return 409, {"error": str(e), "model": model}
        # an explicit swap is an operator/rollout decision: it retries
        # a publish the poll loop is holding after an earlier failure
        outcome = w.check_once(epoch=epoch, force=True)
        return (200 if outcome.get("ok") else 409), outcome

    def epochs(self):
        """{model: served epoch or None} — the rollout-progress signal
        (/healthz + /stats; the fleet router shows it per replica)."""
        return {name: self.pool.get(name).loaded_epoch
                for name in self.pool.names()}

    # -- admission ---------------------------------------------------------
    def admit(self, model):
        """(accepted, http_status, reason) — the load-shedding decision,
        taken BEFORE the request queues."""
        return self._admit(self.batcher(model))

    def _admit(self, b):
        if self.draining:
            return False, 503, "draining"
        if b.depth >= self.max_queue:
            self.stats.inc("shed_queue")
            return False, 429, "queue depth %d at bound %d" % (
                b.depth, self.max_queue)
        if self.slo_ms > 0:
            est = b.estimate_wait_ms()
            if est > self.slo_ms:
                self.stats.inc("shed_slo")
                return False, 429, ("estimated wait %.1fms exceeds SLO "
                                    "%.0fms" % (est, self.slo_ms))
        return True, 200, None

    def handle_predict(self, model, inputs, entry=None, priority=0,
                       deadline_ms=None, tenant=None, request_id=None):
        """Admission + batch + wait; returns ``(status, payload_dict)``.
        Usable without the HTTP layer (tests, in-process serving).
        ``entry`` skips the pool lookup when the caller (the HTTP
        handler's 404 check) already resolved it.  ``priority``,
        ``deadline_ms`` and ``tenant`` pass through to
        :meth:`BucketBatcher.submit` (deadline expiry answers 429
        ``shed_deadline``; a tenant at its queued quota answers 429
        ``shed_tenant``).

        ``request_id`` (the ``X-MXTPU-Request-Id`` header / body
        ``request_id`` field) engages the exactly-once layer: a
        duplicate of a completed request replays the cached response
        bytes without touching admission or the batcher (the
        ``accepted`` counter does not move), a duplicate of an
        in-flight request waits for the original instead of executing
        twice."""
        # gray-failure stand-in: an armed `slow_replica` delays the
        # whole request path (admission included), exactly like a
        # replica whose host is sick — probes stay fast, serving slows
        if faults.consume(SLOW_REPLICA_FAULT):
            slept = faults.hang_seconds(SLOW_REPLICA_FAULT,
                                        SLOW_REPLICA_DEFAULT_S)
            time.sleep(slept)
            # the injected stall must show up in the replica's
            # REPORTED latency window (latency_ms.p99_recent) — the
            # batcher only times queue+exec, and that window is what
            # the controller's outlier detector watches
            self.stats.record_latency(slept * 1000.0)
        if not request_id:
            return self._predict_core(model, inputs, entry, priority,
                                      deadline_ms, tenant)
        key = (model, tenant or "", str(request_id))
        kind, val = self.dedup.claim(key)
        if kind == "replay":
            status, body = val
            return status, json.loads(body.decode("utf-8"))
        if kind == "join":
            if not val.event.wait(timeout=self.request_timeout):
                self.stats.inc("errors")
                return 504, {"error": "duplicate of request %r timed "
                             "out waiting for the original"
                             % (request_id,), "model": model}
            return val.status, json.loads(val.body.decode("utf-8"))
        try:
            status, payload = self._predict_core(
                model, inputs, entry, priority, deadline_ms, tenant)
        except BaseException:
            # never strand duplicates parked on the pending slot; the
            # synthesized 500 is NOT cached (only 200s replay), so a
            # later client retry of this id re-executes cleanly
            self.dedup.complete(key, val, 500, json.dumps(
                {"error": "original execution of request %r failed"
                 % (request_id,), "model": model}).encode("utf-8"))
            raise
        self.dedup.complete(key, val, status,
                            json.dumps(payload).encode("utf-8"))
        return status, payload

    def _predict_core(self, model, inputs, entry, priority, deadline_ms,
                      tenant):
        if entry is None:
            entry = self.pool.get(model)
        if entry.sample_shapes is not None:
            # a client error must be a 400, not a 500 from deep inside
            # the batch forward — and a WRONG first request must never
            # pin the model's per-sample shapes
            got = {k: tuple(np.shape(v)) for k, v in inputs.items()}
            want = {k: tuple(s) for k, s in entry.sample_shapes.items()}
            if got != want:
                return 400, {"error": "input shapes %s != model's %s"
                             % (got, want), "model": model}
        b = self.batcher(model, entry=entry)
        status, err, outs, ms = self._submit_wait(
            b, model, inputs, priority, deadline_ms, tenant)
        if err is not None:
            return status, err
        return 200, {"model": model,
                     "outputs": [np.asarray(o).tolist() for o in outs],
                     "ms": ms}

    def _submit_wait(self, b, model, inputs, priority, deadline_ms,
                     tenant):
        """Admission + queue + wait on ONE batcher — the shared tail of
        :meth:`handle_predict` and :meth:`handle_predict_seq`.  Returns
        ``(status, error_payload_or_None, outputs, ms)``."""
        ok, status, reason = self._admit(b)
        if not ok:
            return status, {"error": reason, "model": model}, None, None
        tic = time.monotonic()
        try:
            fut = b.submit(inputs, priority=priority,
                           deadline_ms=deadline_ms, tenant=tenant)
            # counted only once the request actually entered the queue
            # — a submit-time shed (spent deadline, drain/bound race)
            # must not inflate `accepted` the way shed_queue/shed_slo
            # don't (the accepted-vs-completed ledger on /stats)
            self.stats.inc("accepted")
            outs = fut.result(timeout=self.request_timeout)
        except TenantQuotaExceeded as e:
            # shed, not failed: the batcher already counted shed_tenant
            return 429, {"error": str(e), "model": model,
                         "reason": "shed_tenant"}, None, None
        except DeadlineExpired as e:
            # shed, not failed: the batcher already counted
            # shed_deadline — same 429 contract as shed_queue/shed_slo
            return 429, {"error": str(e), "model": model,
                         "reason": "shed_deadline"}, None, None
        except (Draining, QueueFull) as e:
            # lost the race with a drain/bound between admit and submit
            self.stats.inc("rejected")
            return (429 if isinstance(e, QueueFull) else 503,
                    {"error": str(e), "model": model}, None, None)
        except TimeoutError as e:
            self.stats.inc("errors")
            return 504, {"error": str(e), "model": model}, None, None
        except Exception as e:  # noqa: BLE001 — the model failed, not us
            self.stats.inc("errors")
            return 500, {"error": "%s: %s" % (type(e).__name__, e),
                         "model": model}, None, None
        self.stats.inc("completed")
        return 200, None, outs, \
            round((time.monotonic() - tic) * 1000.0, 3)

    # -- bucketed sequence serving (serving/sequence.py) -------------------
    def seq_batcher(self, model, seq_len, entry=None):
        """The (model, length-bucket) batcher, created on first use
        under the key ``model@seq<L>`` (its own /stats row)."""
        from .sequence import SequenceEntry, seq_batcher_name
        key = seq_batcher_name(model, seq_len)
        with self._lock:
            b = self._batchers.get(key)
        if b is not None:
            return b
        if entry is None:
            entry = self.pool.get(model)
        return self.batcher(key, entry=SequenceEntry(entry, seq_len))

    def handle_predict_seq(self, model, tokens, entry=None, priority=0,
                           deadline_ms=None, tenant=None):
        """One variable-length token sequence in, its per-step outputs
        (trimmed back to the TRUE length) out — the bucketed sequence
        path (serving/sequence.py).  Same status contract as
        :meth:`handle_predict`, plus 400 for a sequence longer than the
        largest configured bucket."""
        from .sequence import parse_seq_buckets, pick_seq_bucket
        if entry is None:
            entry = self.pool.get(model)
        try:
            if self._seq_buckets is None:
                self._seq_buckets = parse_seq_buckets(self.seq_buckets)
            arr = np.asarray(tokens, dtype=np.float32)
            if arr.ndim != 1 or not arr.size:
                raise MXNetError("tokens must be a non-empty flat list, "
                                 "got shape %s" % (arr.shape,))
            bucket = pick_seq_bucket(arr.shape[0], self._seq_buckets)
        except MXNetError as e:
            return 400, {"error": str(e), "model": model}
        n = int(arr.shape[0])
        if n < bucket:
            # edge-pad with the LAST real token (the pad_to_bucket
            # rule): the causal scan never lets pad steps reach the
            # real ones, and repeating a real id can't leave the
            # embedding table the way an invalid filler id could
            arr = np.concatenate([arr, np.repeat(arr[-1:], bucket - n)])
        names = getattr(entry, "input_names", None) or ["data"]
        data_name = "data" if "data" in names else names[0]
        b = self.seq_batcher(model, bucket, entry=entry)
        status, err, outs, ms = self._submit_wait(
            b, model, {data_name: arr}, priority, deadline_ms, tenant)
        if err is not None:
            return status, err
        trimmed = []
        for o in outs:
            o = np.asarray(o)
            if o.ndim and o.shape[0] == bucket:
                o = o[:n]
            trimmed.append(o.tolist())
        return 200, {"model": model, "bucket": bucket, "len": n,
                     "outputs": trimmed, "ms": ms}

    def stats_payload(self):
        payload = self.stats.snapshot()
        payload["models"] = self.pool.names()
        payload["queue_depth"] = self.queue_depths()
        with self._lock:
            batchers = dict(self._batchers)
        # the routing signal a fleet front end spills on: per-model
        # estimated queue wait (docs/how_to/fleet.md)
        payload["est_wait_ms"] = {
            name: round(b.estimate_wait_ms(), 3)
            for name, b in batchers.items()}
        # per-tenant queued depth (the fairness surface): only models
        # with tenant-labeled work show up, so the single-tenant
        # payload is byte-identical to before
        tenants = {name: depths for name, b in batchers.items()
                   for depths in [b.tenant_depths()] if depths}
        if tenants:
            payload["tenants"] = tenants
        # the exactly-once surface: live dedup-cache occupancy (hit/
        # eviction counters ride the shared counters block)
        payload["dedup"] = self.dedup.export()
        payload["draining"] = self.draining
        payload["buckets"] = list(self.buckets)
        payload["epochs"] = self.epochs()
        with self._lock:
            watchers = dict(self.watchers)
        if watchers:
            payload["deploy"] = {name: w.stats()
                                 for name, w in watchers.items()}
        return payload

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Bind the server + start the watchdog monitor; returns self.
        ``self.port`` holds the real port (use port=0 for ephemeral)."""
        if self._server is not None:
            return self
        frontend = self

        class Handler(_Handler):
            fe = frontend

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        # handler threads must outlive shutdown() so drained requests
        # still get their responses written
        self._server.daemon_threads = False
        self._server.block_on_close = True
        self.port = self._server.server_address[1]
        return self

    def serve_forever(self):
        """Blocking accept loop (the daemon's main thread); returns
        after :meth:`drain_and_stop` completes."""
        self.start()
        try:
            self._server.serve_forever(poll_interval=0.1)
        finally:
            self._server.server_close()
            self._stopped.set()

    def serve_in_background(self):
        """start() + serve_forever on a helper thread (tests)."""
        self.start()
        t = threading.Thread(target=self.serve_forever,
                             name="mxserve-http", daemon=True)
        t.start()
        return self

    def drain_and_stop(self, timeout=30.0):
        """The SIGTERM path: stop admitting, finish every accepted
        request, then stop the server.  Idempotent."""
        self.draining = True
        with self._lock:
            watchers = list(self.watchers.values())
            batchers = list(self._batchers.values())
        for w in watchers:
            # no swap may hold the dispatch boundary while the drain
            # waits on those same batchers
            w.stop()
        for b in batchers:
            b.close(drain=True, timeout=timeout)
        with self._lock:
            watchdogs, self._watchdogs = self._watchdogs, []
        for wd in watchdogs:
            wd.stop()
        if self._server is not None:
            self._server.shutdown()

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT)):
        """SIGTERM/SIGINT -> graceful drain (handler returns immediately;
        a helper thread does the drain so the accept loop isn't blocked
        inside the signal frame)."""
        def _on_signal(signum, frame):
            threading.Thread(target=self.drain_and_stop,
                             name="mxserve-drain", daemon=True).start()
        for sig in signals:
            signal.signal(sig, _on_signal)
        return self

    def wait_stopped(self, timeout=None):
        return self._stopped.wait(timeout)


class _Handler(BaseHTTPRequestHandler):
    """Routes onto the owning :class:`ServingFrontend` (``fe`` class
    attr, set by ``start()``)."""

    fe = None
    protocol_version = "HTTP/1.1"
    #: socket timeout: an IDLE keep-alive connection parks its handler
    #: thread in readline() — with block_on_close joining handler
    #: threads at shutdown, a single idle client (a monitoring poller,
    #: an unclosed ServeClient) would otherwise wedge the SIGTERM drain
    #: forever.  On timeout http.server closes the connection, so the
    #: drain's thread joins are bounded by ~this many seconds.  (It
    #: does NOT bound an in-flight predict — that blocks in do_POST,
    #: not in a socket read.)
    timeout = 10.0

    def log_message(self, fmt, *args):  # per-request stderr spam off
        pass

    def _reply(self, status, payload):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._reply(200, {
                "status": "draining" if self.fe.draining else "ok",
                "models": self.fe.pool.names(),
                "epochs": self.fe.epochs()})
        elif self.path == "/stats":
            self._reply(200, self.fe.stats_payload())
        else:
            self._reply(404, {"error": "unknown path %r" % self.path})

    def _qos(self, payload=None):
        """(priority, deadline_ms, tenant, request_id) from the
        ``X-MXTPU-Priority`` / ``X-MXTPU-Deadline-Ms`` /
        ``X-MXTPU-Tenant`` / ``X-MXTPU-Request-Id`` headers, overridden
        by same-named JSON body fields (``priority`` / ``deadline_ms``
        / ``tenant`` / ``request_id``) when present."""
        priority = self.headers.get("X-MXTPU-Priority")
        deadline = self.headers.get("X-MXTPU-Deadline-Ms")
        tenant = self.headers.get("X-MXTPU-Tenant")
        request_id = self.headers.get("X-MXTPU-Request-Id")
        if payload is not None and isinstance(payload, dict):
            priority = payload.get("priority", priority)
            deadline = payload.get("deadline_ms", deadline)
            tenant = payload.get("tenant", tenant)
            request_id = payload.get("request_id", request_id)
        return (int(priority) if priority is not None else 0,
                float(deadline) if deadline is not None else None,
                str(tenant) if tenant is not None else None,
                str(request_id) if request_id is not None else None)

    def _parse_inputs(self, entry):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype == "application/x-npy":
            import io as _pyio
            arr = np.load(_pyio.BytesIO(body), allow_pickle=False)
            return {entry.input_names[0]:
                    np.ascontiguousarray(arr, dtype=np.float32)}, \
                self._qos()
        payload = json.loads(body.decode("utf-8"))
        raw = payload.get("inputs", payload)
        inputs = {}
        for k, v in raw.items():
            if k in entry.input_names:
                inputs[k] = np.asarray(v, dtype=np.float32)
        if set(inputs) != set(entry.input_names):
            raise ValueError("need inputs %s, got %s"
                             % (entry.input_names, sorted(raw)))
        return inputs, self._qos(payload)

    def do_POST(self):
        if self.path.startswith("/swap/"):
            # the continuous-deployment admin surface: promote the
            # newest verified epoch (or body {"epoch": N}) for a model
            model = self.path[len("/swap/"):].strip("/")
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                payload = json.loads(body.decode("utf-8")) if body else {}
                epoch = payload.get("epoch")
            except Exception as e:  # noqa: BLE001 — malformed body
                self._reply(400, {"error": "bad request body: %s" % (e,)})
                return
            status, out = self.fe.handle_swap(model, epoch=epoch)
            self._reply(status, out)
            return
        if self.path.startswith("/predict_seq/"):
            # the bucketed-sequence path: body {"tokens": [...ids...]}
            model = self.path[len("/predict_seq/"):].strip("/")
            try:
                entry = self.fe.pool.get(model)
            except MXNetError as e:
                self._reply(404, {"error": str(e)})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length)
                                     .decode("utf-8"))
                tokens = payload["tokens"]
                # dedup is scoped to /predict — the request id (if any)
                # is ignored on the sequence path
                priority, deadline_ms, tenant, _ = self._qos(payload)
            except Exception as e:  # noqa: BLE001 — malformed body
                self._reply(400, {"error": "bad request body: %s" % (e,)})
                return
            status, out = self.fe.handle_predict_seq(
                model, tokens, entry=entry, priority=priority,
                deadline_ms=deadline_ms, tenant=tenant)
            self._reply(status, out)
            return
        if not self.path.startswith("/predict/"):
            self._reply(404, {"error": "unknown path %r" % self.path})
            return
        model = self.path[len("/predict/"):].strip("/")
        try:
            entry = self.fe.pool.get(model)
        except MXNetError as e:
            self._reply(404, {"error": str(e)})
            return
        try:
            inputs, (priority, deadline_ms, tenant, request_id) = \
                self._parse_inputs(entry)
        except Exception as e:  # noqa: BLE001 — malformed client body
            self._reply(400, {"error": "bad request body: %s" % (e,)})
            return
        status, payload = self.fe.handle_predict(
            model, inputs, entry=entry, priority=priority,
            deadline_ms=deadline_ms, tenant=tenant,
            request_id=request_id)
        self._reply(status, payload)


class ServeClient(object):
    """Minimal keep-alive client for the daemon (tests, bench, drills).
    One instance per thread — ``http.client`` connections are not
    thread-safe."""

    #: retire an idle keep-alive connection before the server side can:
    #: the daemon handler's 10s socket timeout closes ITS end of an
    #: idle connection, and the next request written onto that socket
    #: surfaces as a spurious transport error — the same bug class the
    #: router's pooled connections had (PR 11's CONN_IDLE_S fix),
    #: load-bearing here now that client retries ride the exactly-once
    #: path and must not be minted by the client's own stale socket
    CONN_IDLE_S = 5.0

    def __init__(self, host, port, timeout=60.0):
        self.host, self.port, self.timeout = host, int(port), timeout
        self._conn = None
        self._last_use = 0.0

    def _connection(self):
        import http.client
        now = time.monotonic()
        if self._conn is not None and \
                now - self._last_use > self.CONN_IDLE_S:
            self.close()
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        self._last_use = now
        return self._conn

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _request(self, method, path, body=None, headers=None):
        # Retry ONLY send-phase failures (a keep-alive socket that died
        # across a server restart surfaces in conn.request).  Once the
        # request is on the wire, a response-phase failure must raise:
        # blindly re-sending a non-idempotent POST /predict would
        # execute it twice (double-counted stats, two queue slots).
        try:
            conn = self._connection()
            conn.request(method, path, body=body, headers=headers or {})
        except Exception:
            self.close()
            conn = self._connection()
            conn.request(method, path, body=body, headers=headers or {})
        try:
            resp = conn.getresponse()
            data = resp.read()
        except Exception:
            self.close()       # the connection is in an unknown state
            if method not in ("GET", "HEAD"):
                raise
            # idempotent request on a keep-alive socket the server shut
            # between requests (RemoteDisconnected): one clean retry
            conn = self._connection()
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
        try:
            payload = json.loads(data.decode("utf-8")) if data else {}
        except ValueError:
            payload = {"raw": data.decode("utf-8", "replace")}
        return resp.status, payload

    def predict(self, model, inputs, npy=False, priority=None,
                deadline_ms=None, tenant=None, request_id=None):
        """``inputs``: {name: per-sample array} (or a bare array for the
        single-input case).  ``priority``/``deadline_ms``/``tenant``
        ride as ``X-MXTPU-*`` headers (work on both body formats).
        Returns ``(status, payload)``.

        Every predict is stamped with an idempotency key
        (``X-MXTPU-Request-Id``, auto-generated unless ``request_id``
        is given) — resending with the SAME id is exactly-once: the
        daemon replays/shares the original execution instead of
        running it twice."""
        if not isinstance(inputs, dict):
            inputs = {"data": inputs}
        qos = {"X-MXTPU-Request-Id":
               str(request_id) if request_id is not None
               else uuid.uuid4().hex}
        if priority is not None:
            qos["X-MXTPU-Priority"] = str(int(priority))
        if deadline_ms is not None:
            qos["X-MXTPU-Deadline-Ms"] = str(float(deadline_ms))
        if tenant is not None:
            qos["X-MXTPU-Tenant"] = str(tenant)
        if npy:
            import io as _pyio
            (name, arr), = inputs.items()
            buf = _pyio.BytesIO()
            np.save(buf, np.asarray(arr, dtype=np.float32))
            return self._request(
                "POST", "/predict/%s" % model, body=buf.getvalue(),
                headers={"Content-Type": "application/x-npy", **qos})
        body = json.dumps(
            {"inputs": {k: np.asarray(v).tolist()
                        for k, v in inputs.items()}}).encode("utf-8")
        return self._request(
            "POST", "/predict/%s" % model, body=body,
            headers={"Content-Type": "application/json", **qos})

    def predict_seq(self, model, tokens, priority=None,
                    deadline_ms=None, tenant=None):
        """POST /predict_seq/<model>: one variable-length token list;
        the daemon buckets, batches, and trims (serving/sequence.py).
        Returns ``(status, payload)`` with per-step ``outputs`` cut to
        the true length."""
        qos = {}
        if priority is not None:
            qos["X-MXTPU-Priority"] = str(int(priority))
        if deadline_ms is not None:
            qos["X-MXTPU-Deadline-Ms"] = str(float(deadline_ms))
        if tenant is not None:
            qos["X-MXTPU-Tenant"] = str(tenant)
        body = json.dumps(
            {"tokens": [int(t) for t in np.asarray(tokens).ravel()]}
        ).encode("utf-8")
        return self._request(
            "POST", "/predict_seq/%s" % model, body=body,
            headers={"Content-Type": "application/json", **qos})

    def swap(self, model, epoch=None):
        """POST /swap/<model>: promote the newest verified epoch (or a
        specific one).  NOT idempotent-retried (it is a POST)."""
        body = json.dumps({} if epoch is None
                          else {"epoch": int(epoch)}).encode("utf-8")
        return self._request(
            "POST", "/swap/%s" % model, body=body,
            headers={"Content-Type": "application/json"})

    def healthz(self):
        return self._request("GET", "/healthz")

    def stats(self):
        return self._request("GET", "/stats")

    def wait_ready(self, deadline_s=60.0):
        """Poll /healthz until the daemon answers; raises on timeout."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                status, payload = self.healthz()
                if status == 200:
                    return payload
            except Exception:  # noqa: BLE001 — not accepting yet
                self.close()
            time.sleep(0.05)
        raise TimeoutError("daemon at %s:%d never became healthy"
                           % (self.host, self.port))
