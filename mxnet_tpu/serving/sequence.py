"""Bucketed SEQUENCE serving: variable-length token streams behind the
same admission-controlled front end as fixed-shape traffic.

This is the serving-side analog of the training-side bucketing iterator
(``rnn/io.BucketSentenceIter`` + ``BucketingModule``): a request carries
one token sequence of arbitrary length; the front end picks the
smallest configured LENGTH bucket that fits, edge-pads the sequence to
it, and batches it with other requests of the SAME bucket — so the
fused-RNN forward (``ops/nn.RNN``'s lax.scan) compiles once per
(batch-bucket, length-bucket) pair and every request rides a warm
executor.  Each (model, length) pair gets its OWN
:class:`~.batcher.BucketBatcher` (registered as ``model@seq<L>``), so
length buckets never cross-contaminate batch shapes and all the QoS
machinery — priority, deadlines, weighted-fair tenants — applies per
bucket unchanged.

Why edge-padding is safe here: the language-model scan is CAUSAL —
step ``t`` depends only on tokens ``<= t`` — so the first ``len``
output steps are independent of whatever the pad region holds, and the
front end trims the answer back to the true length before replying.
``tests/test_serving.py`` pins this as the BIT-STABILITY contract: the
same prefix served through two different length buckets answers
identically on the real steps.

The one model-shape fact this module owns: the reference LM head
(``models/lstm_lm.lstm_lm_sym``) emits its softmax as ``(L*B, V)``
rows in TIME-MAJOR interleave (row ``t*B + b``).  The batcher splits
batches on axis 0, so :class:`SequenceEntry` re-lays such outputs to
``(B, L, V)`` before handing them back.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError, get_env, register_env

__all__ = ["SequenceEntry", "parse_seq_buckets", "pick_seq_bucket",
           "seq_batcher_name", "ENV_SERVE_SEQ_BUCKETS"]

ENV_SERVE_SEQ_BUCKETS = register_env(
    "MXTPU_SERVE_SEQ_BUCKETS", default="8,16,32,64",
    doc="Sequence-LENGTH buckets for POST /predict_seq/<model> "
        "(comma-separated, ascending). A request's token list is "
        "edge-padded to the smallest bucket that fits; longer than the "
        "largest bucket is a 400. Each (model, bucket) pair batches "
        "independently")


def parse_seq_buckets(spec=None):
    """``"8,16,32"`` (or any int iterable) -> ascending unique tuple.
    ``None`` reads ``MXTPU_SERVE_SEQ_BUCKETS``."""
    if spec is None:
        spec = get_env(ENV_SERVE_SEQ_BUCKETS)
    if isinstance(spec, str):
        spec = [tok for tok in spec.replace(";", ",").split(",")
                if tok.strip()]
    try:
        buckets = sorted({int(b) for b in spec})
    except (TypeError, ValueError):
        raise MXNetError("bad sequence-bucket spec %r (want e.g. "
                         "'8,16,32')" % (spec,))
    if not buckets or buckets[0] < 1:
        raise MXNetError("sequence buckets must be positive ints, got %r"
                         % (spec,))
    return tuple(buckets)


def pick_seq_bucket(length, buckets):
    """Smallest bucket >= ``length`` (the BucketSentenceIter rule);
    raises :class:`MXNetError` when the sequence is longer than every
    bucket — the caller answers 400, never truncates silently."""
    n = int(length)
    if n < 1:
        raise MXNetError("empty token sequence")
    for b in buckets:
        if n <= b:
            return b
    raise MXNetError("sequence length %d exceeds the largest bucket %d"
                     % (n, buckets[-1]))


def seq_batcher_name(model, seq_len):
    """The per-(model, length-bucket) batcher key — shows up as its own
    row in ``/stats`` ``queue_depth``/``est_wait_ms``."""
    return "%s@seq%d" % (model, int(seq_len))


class SequenceEntry(object):
    """A per-(model, length-bucket) view of a pooled model, shaped like
    a :class:`~.pool.PooledModel` where the batcher is concerned
    (``input_names``/``sample_shapes``/``forward``).

    It forwards the token input plus a ZERO loss label of the same
    ``(B, L)`` shape (shape inference cannot derive a label's shape
    from the data side, and inference ignores its value) — the fused
    RNN's init states stay free symbol args the
    :class:`~..predict.Predictor` zero-fills at their back-inferred
    ``(layers, B, H)`` shape, which is exactly the zero initial state
    the training side used, at whatever batch bucket this batch
    happens to run.  Outputs whose leading axis is the time-major
    ``L*B`` interleave are re-laid to batch-major ``(B, L, ...)`` so
    the batcher's axis-0 per-request split holds.
    """

    def __init__(self, base, seq_len, data_name=None):
        self.base = base
        self.seq_len = int(seq_len)
        if data_name is None:
            names = getattr(base, "input_names", None) or ["data"]
            data_name = "data" if "data" in names else names[0]
        self.data_name = data_name
        self.input_names = [data_name]
        self.sample_shapes = {data_name: (self.seq_len,)}
        #: free label args (not in the loaded params): fed zeros at the
        #: data's shape so per-bucket shape inference completes
        symbol = getattr(base, "symbol", None)
        loaded = getattr(base, "arg_params", None) or {}
        self.label_names = [
            n for n in (symbol.list_arguments() if symbol is not None
                        else ())
            if n.endswith("label") and n not in loaded]

    @property
    def loaded_epoch(self):
        return self.base.loaded_epoch

    def _relay(self, out, batch):
        """Time-major ``(L*B, ...)`` -> batch-major ``(B, L, ...)``;
        anything already batch-major (or unbatched) passes through."""
        out = np.asarray(out)
        if out.ndim >= 1 and batch and \
                out.shape[0] == self.seq_len * batch and \
                out.shape[0] != batch:
            out = out.reshape((self.seq_len, batch) + out.shape[1:])
            out = np.swapaxes(out, 0, 1)
        return out

    def forward(self, inputs, n_valid=None):
        data = np.asarray(inputs[self.data_name])
        batch = int(data.shape[0]) if data.ndim else 0
        feed = {self.data_name: data}
        for name in self.label_names:
            feed[name] = np.zeros(data.shape, dtype=np.float32)
        outs = self.base.forward(feed, n_valid=n_valid)
        return [self._relay(o, batch) for o in outs]
