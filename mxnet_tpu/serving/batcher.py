"""Request batcher: single requests in, padded bucket-shaped batches out.

The serving analog of Orca's iteration-level batching / Clipper's
adaptive batching, shaped for XLA: every dispatched batch has one of a
fixed set of power-of-two **bucket** sizes, so each bucket hits exactly
ONE cached AOT-compiled forward (``predict.Predictor``'s per-shape jit
cache, persisted across relaunches by ``MXTPU_COMPILE_CACHE``) instead
of recompiling per arrival count.

Dispatch policy (continuous batching): the dispatcher takes everything
queued the moment the previous forward finishes — under load the
in-flight batch IS the wait window, so throughput needs no added
latency.  Only when the queue is smaller than the largest bucket does a
max-wait timer (``MXTPU_SERVE_MAX_WAIT_MS``, measured from the OLDEST
queued request) hold the batch open for stragglers.

PRIORITY + DEADLINES (the anti-starvation half of the SLO story): a
request may carry ``priority`` (higher dispatches first; default 0) and
``deadline_ms`` (a per-request latency budget).  The dispatcher fills
each bucket highest-priority-first — FIFO *within* a priority level, so
equal-priority traffic keeps the exact historical order — and a queued
request whose deadline passes before dispatch is EXPIRED with
:class:`DeadlineExpired` (HTTP 429, ``shed_deadline`` on ``/stats``)
instead of being served as dead work the client already gave up on.
Strictly-FIFO dispatch let one slow tenant hold every later request's
latency hostage; priority ordering bounds that blast radius without
touching the bit-exactness contract (a request's result never depends
on its co-batched rows — only WHEN it runs changes).

TENANT FAIRNESS (weighted-fair queueing): a request may also carry a
``tenant`` label.  Each tenant gets its own queue and a **stride
scheduler** picks which tenant fills the next bucket slot: every pop
charges the tenant's virtual *pass* by ``1/weight``
(``MXTPU_SERVE_TENANT_WEIGHTS``, e.g. ``gold:4,free:1``; unlisted
tenants weigh 1) and the lowest pass goes next — so over any window,
service converges to the weight ratio NO MATTER how hard one tenant
floods.  A tenant reactivating after idling is clamped to the current
virtual time (no banked credit), a per-tenant queued-request quota
(``MXTPU_SERVE_TENANT_QUOTA``) sheds a flooder at admission with
:class:`TenantQuotaExceeded` (HTTP 429, ``shed_tenant``) before it
occupies the shared queue bound, and the existing semantics survive
inside each tenant untouched: priority desc / FIFO within a level per
tenant, deadline expiry everywhere, and the global anti-starvation
floor rides the ELDEST queued request across all tenants.  Requests
that never set a tenant share one default bucket — single-tenant
traffic dispatches in the exact historical order.

BIT-EXACTNESS CONTRACT: a request's result depends only on its own
bytes and the bucket shape it ran at — never on batch fill, its row
position, or co-batched requests.  (XLA re-tiles reductions per batch
shape, so results ARE shape-dependent — measured ~1e-13..1e-7 per-row
deltas between batch-1 and batch-8 MLP forwards on CPU — which is
exactly why buckets exist: one canonical program per bucket.  Within a
fixed shape, rows of row-independent inference graphs are bit-stable;
``tests/test_serving.py`` proves both halves.)  Padding replicates the
last real row rather than injecting zeros, so padding can never create
NaN/Inf paths the real rows didn't have.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time

import numpy as np

from ..base import MXNetError, get_env, register_env
from ..resilience import faults

__all__ = ["BucketBatcher", "QueueFull", "Draining", "DeadlineExpired",
           "TenantQuotaExceeded", "parse_buckets", "pick_bucket",
           "pad_to_bucket", "parse_tenant_weights", "DEFAULT_TENANT",
           "ENV_SERVE_BUCKETS", "ENV_SERVE_MAX_WAIT_MS",
           "ENV_SERVE_TENANT_WEIGHTS", "ENV_SERVE_TENANT_QUOTA"]

ENV_SERVE_BUCKETS = register_env(
    "MXTPU_SERVE_BUCKETS", default="1,2,4,8,16,32",
    doc="Comma-separated ascending batch-size buckets for the serving "
        "batcher; each bucket is one cached compiled forward")
ENV_SERVE_MAX_WAIT_MS = register_env(
    "MXTPU_SERVE_MAX_WAIT_MS", default=2.0,
    doc="How long a dispatching batch may hold the queue open for "
        "stragglers, measured from the oldest queued request (ms)")
ENV_SERVE_TENANT_WEIGHTS = register_env(
    "MXTPU_SERVE_TENANT_WEIGHTS", default="",
    doc="Weighted-fair tenant shares for the serving batcher, e.g. "
        "'gold:4,free:1'; unlisted tenants (and requests with no "
        "tenant) weigh 1; empty = all tenants equal")
ENV_SERVE_TENANT_QUOTA = register_env(
    "MXTPU_SERVE_TENANT_QUOTA", default=0,
    doc="Per-tenant queued-request bound in the serving batcher: a "
        "tenant at its quota is shed with HTTP 429 (shed_tenant) while "
        "everyone else keeps queueing; 0 = unbounded")

#: the tenant label for requests that never set one — single-tenant
#: traffic all lands here and dispatches in the exact pre-WFQ order
DEFAULT_TENANT = ""

#: fault points on the batch forward: ``serve_forward`` (arm = failing
#: model, arm_hang = a timed stall) and ``hang_serve_forward`` (a
#: maybe_hang site, so ``MXTPU_FAULTS=hang_serve_forward:1`` wedges the
#: dispatch for the default 3600s from the ENV alone — the watchdog
#: drill's wedged-forward window, same plumbing as ``hang_step``)
SERVE_FORWARD_FAULT = "serve_forward"
SERVE_FORWARD_HANG = "hang_serve_forward"


class QueueFull(MXNetError):
    """Admission refused: the request queue is at its bound."""


class Draining(MXNetError):
    """Admission refused: the daemon is draining for shutdown."""


class DeadlineExpired(MXNetError):
    """The request's deadline passed before its batch dispatched (HTTP
    429, ``shed_deadline``) — the client has already given up, so
    serving it would burn a bucket slot on dead work."""


class TenantQuotaExceeded(MXNetError):
    """The request's tenant already has ``MXTPU_SERVE_TENANT_QUOTA``
    requests queued (HTTP 429, ``shed_tenant``) — the flood is shed at
    admission, before it can occupy the shared queue bound and starve
    every other tenant's admission too."""


def parse_buckets(spec=None):
    """``"1,2,4,8"`` (or an int list) -> validated ascending tuple."""
    if spec is None:
        spec = get_env(ENV_SERVE_BUCKETS)
    if isinstance(spec, str):
        try:
            buckets = tuple(int(p) for p in spec.replace(" ", "").split(",")
                            if p)
        except ValueError:
            raise MXNetError("bad bucket spec %r (want e.g. '1,2,4,8')"
                             % (spec,))
    else:
        buckets = tuple(int(b) for b in spec)
    if not buckets or any(b <= 0 for b in buckets) or \
            list(buckets) != sorted(set(buckets)):
        raise MXNetError("buckets must be positive, ascending, unique: %r"
                         % (buckets,))
    return buckets


def parse_tenant_weights(spec=None):
    """``"gold:4,free:1"`` (or a dict) -> ``{tenant: weight}``; empty
    means every tenant weighs 1.  Weights must be > 0 — a zero share is
    a ban, and bans belong at admission (the quota), not in the
    scheduler where they would starve silently."""
    if spec is None:
        spec = get_env(ENV_SERVE_TENANT_WEIGHTS)
    if isinstance(spec, dict):
        pairs = list(spec.items())
    else:
        spec = (spec or "").strip()
        if not spec:
            return {}
        pairs = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise MXNetError("bad tenant weight %r (want "
                                 "'tenant:share')" % (part,))
            name, share = part.rsplit(":", 1)
            pairs.append((name.strip(), share))
    out = {}
    for name, share in pairs:
        try:
            w = float(share)
        except (TypeError, ValueError):
            raise MXNetError("bad tenant weight share %r for %r"
                             % (share, name))
        if w <= 0:
            raise MXNetError(
                "tenant %r weight must be > 0 (got %r) — to ban a "
                "tenant use the quota, not a zero share" % (name, w))
        out[name] = w
    return out


def pick_bucket(n, buckets):
    """Smallest bucket >= ``n`` — NEVER a truncating one.  ``n`` above
    the largest bucket is a caller error (the batcher caps batches at
    the largest bucket before picking)."""
    for b in buckets:
        if b >= n:
            return b
    raise MXNetError("request count %d exceeds the largest bucket %d"
                     % (n, buckets[-1]))


def pad_to_bucket(rows, bucket):
    """Stack per-sample rows and edge-pad (repeat the last real row) to
    ``bucket``.  Returns the (bucket, \\*sample) array."""
    stacked = np.stack(rows)
    n = stacked.shape[0]
    if n == bucket:
        return stacked
    pad = np.repeat(stacked[-1:], bucket - n, axis=0)
    return np.concatenate([stacked, pad], axis=0)


class _Future(object):
    """Single-consumer result slot for one queued request."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_error(self, exc):
        self._error = exc
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("request did not complete within %ss"
                               % timeout)
        if self._error is not None:
            raise self._error
        return self._result


class _Request(object):
    __slots__ = ("inputs", "future", "enqueued_at", "priority",
                 "deadline", "seq", "tenant")

    def __init__(self, inputs, priority=0, deadline=None, seq=0,
                 tenant=DEFAULT_TENANT):
        self.inputs = inputs
        self.future = _Future()
        self.enqueued_at = time.monotonic()
        self.priority = int(priority)
        self.deadline = deadline            # absolute monotonic, or None
        self.seq = seq
        self.tenant = tenant

    def heap_key(self):
        """Dispatch order WITHIN a tenant: highest priority first, FIFO
        (arrival seq) within a priority level — the historical
        strict-FIFO order is the seq tiebreak, so equal-priority
        traffic is untouched."""
        return (-self.priority, self.seq)


class BucketBatcher(object):
    """One model's queues + dispatcher thread.

    ``runner(inputs, n_valid)`` receives ``{input_name: (bucket, *sample)
    float32 array}`` and returns a list of per-output ``(bucket, ...)``
    arrays; the batcher splits rows back out to the waiting futures.
    All forwards for the model happen on this one dispatcher thread, so
    the underlying ``Predictor`` needs no locking.
    """

    #: bound on DISTINCT tenant queues (the fairness table must stay a
    #: scan-able dict, not an unbounded attacker-controlled map):
    #: tenant number MAX_TENANTS+1 folds into the default bucket — it
    #: still gets served, it just shares the default tenant's turn
    MAX_TENANTS = 64

    def __init__(self, runner, buckets=None, max_wait_ms=None,
                 max_queue=None, name="model", watchdog=None, stats=None,
                 tenant_weights=None, tenant_quota=None):
        self.runner = runner
        self.name = name
        self.buckets = parse_buckets(buckets)
        if max_wait_ms is None:
            max_wait_ms = float(get_env(ENV_SERVE_MAX_WAIT_MS))
        self.max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self.max_queue = max_queue          # None = unbounded (frontend
        self.watchdog = watchdog            # owns admission control)
        self.stats = stats
        self.tenant_weights = parse_tenant_weights(tenant_weights)
        self.tenant_quota = int(get_env(ENV_SERVE_TENANT_QUOTA)
                                if tenant_quota is None else tenant_quota)
        self._cv = threading.Condition()
        #: {tenant: heap of (heap_key, _Request)} — per-tenant queues;
        #: single-tenant traffic all lives under DEFAULT_TENANT and
        #: dispatches in the exact pre-WFQ heap order
        self._queues = {}
        #: {tenant: virtual pass} — the stride scheduler state: every
        #: pop charges 1/weight; lowest pass fills the next slot
        self._passes = {}
        #: current virtual time = the pass of the last tenant chosen
        #: (pre-charge); a reactivating tenant is clamped up to it so
        #: idling never banks credit
        self._vtime = 0.0
        self._seq = itertools.count()
        #: queued requests carrying a deadline — the common
        #: deadline-less workload keeps the dispatcher's expiry check
        #: O(1) instead of scanning the heaps every wake
        self._deadlines = 0
        self._inflight = 0
        self._draining = False
        self._closing = False
        #: run_exclusive() gate: while set, the dispatcher takes no new
        #: batch (queued requests WAIT, they are never dropped) — the
        #: hot-swap dispatch boundary (serving/deploy.py)
        self._paused = False
        self._ema_batch_s = None            # EMA of batch service time
        self._sample_shapes = None          # fixed by the first request
        self._thread = threading.Thread(
            target=self._loop, name="mxserve-batch-%s" % name, daemon=True)
        self._thread.start()

    # -- WFQ internals (call with _cv held) --------------------------------
    def _qtotal_locked(self):
        return sum(len(q) for q in self._queues.values())

    def _weight(self, tenant):
        return float(self.tenant_weights.get(tenant, 1.0))

    def _charge_locked(self, tenant):
        self._passes[tenant] = self._passes.get(tenant, 0.0) \
            + 1.0 / self._weight(tenant)

    def _pop_next_locked(self):
        """One stride-scheduler step: lowest-pass tenant with queued
        work pops ITS best request (priority desc, FIFO within) and
        pays 1/weight.  Name tiebreak keeps ties deterministic."""
        tenant = min((t for t, q in self._queues.items() if q),
                     key=lambda t: (self._passes.get(t, 0.0), t))
        self._vtime = self._passes.get(tenant, 0.0)
        req = heapq.heappop(self._queues[tenant])[1]
        self._charge_locked(tenant)
        return req

    def _all_queued_locked(self):
        for q in self._queues.values():
            for entry in q:
                yield entry[1]

    def tenant_depths(self):
        """{tenant: queued count} for every tenant with queued work
        (the /stats fairness surface; the default tenant shows as
        ``""``)."""
        with self._cv:
            return {t: len(q) for t, q in self._queues.items() if q}

    # -- producer side -----------------------------------------------------
    @property
    def depth(self):
        """Queued + in-flight request count (the admission gauge)."""
        with self._cv:
            return self._qtotal_locked() + self._inflight

    def estimate_wait_ms(self):
        """Rough time a NEW request would spend queued: the work ahead
        of it (queued + in-flight rows, in units of largest-bucket
        batches) x the EMA batch service time.  0 for an empty queue or
        until the first batch has been timed (admit optimistically)."""
        with self._cv:
            depth = self._qtotal_locked() + self._inflight
            ema = self._ema_batch_s
        if not ema or not depth:
            return 0.0
        return depth / float(self.buckets[-1]) * ema * 1000.0

    def submit(self, inputs, priority=0, deadline_ms=None, tenant=None):
        """Queue one request (``{input_name: per-sample float32 array}``,
        NO batch dimension) -> future.  ``priority``: higher dispatches
        first (default 0 — all-equal keeps strict FIFO).  ``deadline_ms``:
        latency budget; a request still queued when it runs out is shed
        with :class:`DeadlineExpired` (a non-positive budget sheds
        immediately).  ``tenant``: the fairness label (None = the
        shared default bucket); a tenant at its queued quota is shed
        with :class:`TenantQuotaExceeded`.  Raises :class:`Draining`
        during shutdown and :class:`QueueFull` at the queue bound."""
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        deadline = None
        if deadline_ms is not None:
            if float(deadline_ms) <= 0:
                if self.stats is not None:
                    self.stats.inc("shed_deadline")
                raise DeadlineExpired(
                    "model %r: deadline budget %.1fms already spent"
                    % (self.name, float(deadline_ms)))
            deadline = time.monotonic() + float(deadline_ms) / 1000.0
        shapes = {k: tuple(np.shape(v)) for k, v in inputs.items()}
        with self._cv:
            if self._draining:
                raise Draining("model %r is draining" % self.name)
            if self.max_queue is not None and \
                    self._qtotal_locked() >= self.max_queue:
                raise QueueFull("model %r queue is at its bound (%d)"
                                % (self.name, self.max_queue))
            if tenant != DEFAULT_TENANT and tenant not in self._queues \
                    and len(self._queues) >= self.MAX_TENANTS:
                tenant = DEFAULT_TENANT     # see MAX_TENANTS
            q = self._queues.get(tenant)
            if self.tenant_quota > 0 and q is not None and \
                    len(q) >= self.tenant_quota:
                if self.stats is not None:
                    self.stats.inc("shed_tenant")
                raise TenantQuotaExceeded(
                    "model %r: tenant %r is at its queued quota (%d) — "
                    "shed, not queued" % (self.name, tenant,
                                          self.tenant_quota))
            if self._sample_shapes is None:
                self._sample_shapes = shapes
            elif shapes != self._sample_shapes:
                raise MXNetError(
                    "request shapes %s do not match the model's %s"
                    % (shapes, self._sample_shapes))
            if q is None:
                q = self._queues[tenant] = []
            if not q:
                # (re)activation: no banked credit from idling — the
                # tenant joins at the CURRENT virtual time, it does not
                # cash in every turn it skipped
                self._passes[tenant] = max(
                    self._passes.get(tenant, 0.0), self._vtime)
            req = _Request(inputs, priority=priority, deadline=deadline,
                           seq=next(self._seq), tenant=tenant)
            heapq.heappush(q, (req.heap_key(), req))
            if deadline is not None:
                self._deadlines += 1
            self._cv.notify_all()
        return req.future

    # -- dispatcher --------------------------------------------------------
    def _loop(self):
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            finally:
                with self._cv:
                    self._inflight = 0
                    self._cv.notify_all()

    def _expire_locked(self):
        """Drop queued requests whose deadline has passed (call with
        ``_cv`` held): their futures raise :class:`DeadlineExpired` and
        ``shed_deadline`` counts them — dispatching them would spend a
        bucket slot on work the client has already abandoned."""
        if not self._deadlines:
            return                  # O(1) for deadline-less traffic
        now = time.monotonic()
        if not any(r.deadline is not None and r.deadline <= now
                   for r in self._all_queued_locked()):
            return
        dead = []
        for tenant, q in self._queues.items():
            live, mine = [], []
            for entry in q:
                req = entry[1]
                if req.deadline is not None and req.deadline <= now:
                    mine.append(req)
                else:
                    live.append(entry)
            if mine:
                heapq.heapify(live)
                self._queues[tenant] = live
                dead.extend(mine)
        self._deadlines -= len(dead)
        for req in dead:
            req.future.set_error(DeadlineExpired(
                "model %r: deadline passed after %.1fms queued"
                % (self.name, (now - req.enqueued_at) * 1000.0)))
            if self.stats is not None:
                self.stats.inc("shed_deadline")

    #: anti-starvation floor: a queued request older than
    #: ``max(8 x max_wait, STARVATION_S)`` seconds claims one slot of
    #: the next batch UNCONDITIONALLY, priority and tenant passes
    #: notwithstanding.  Without it, sustained higher-priority arrivals
    #: at >= service rate could hold a low-priority request in the
    #: queue forever (the max-wait timer forces *a* dispatch, not *its*
    #: dispatch) — priorities delay work, they must never starve it.
    #: The floor rides the GLOBAL eldest across all tenants (and still
    #: charges its tenant's pass: guaranteed progress, not free
    #: service).  One slot per batch gives the aged head-of-line
    #: guaranteed progress while the rest of the bucket still fills by
    #: the fair-share order.
    STARVATION_S = 0.25

    def _next_batch(self):
        """Block for the first request, then hold the batch open until
        the largest bucket fills or the oldest request ages past
        max_wait (draining skips the wait — flush what is queued).
        Slot-fill order is the stride scheduler's (lowest tenant pass;
        priority desc / FIFO within the tenant) — except that a request
        past the starvation bound rides first (see
        :data:`STARVATION_S`); past-deadline entries are expired, never
        dispatched."""
        cap = self.buckets[-1]
        with self._cv:
            while True:
                self._expire_locked()
                if self._paused and not self._closing:
                    # a hot swap holds the dispatch boundary: requests
                    # keep queueing, the next batch waits for the new
                    # weights (a close() overrides — shutdown wins)
                    self._cv.wait(0.05)
                    continue
                total = self._qtotal_locked()
                if not total:
                    if self._closing:
                        return None
                    self._cv.wait(0.1)
                    continue
                # max-wait is measured from the OLDEST queued request
                # regardless of priority or tenant — a low-priority
                # straggler cannot be deferred past the wait bound
                oldest = min(r.enqueued_at
                             for r in self._all_queued_locked())
                left = self.max_wait - (time.monotonic() - oldest)
                if total >= cap or self._draining or left <= 0:
                    break
                self._cv.wait(min(left, 0.02))
            take = min(self._qtotal_locked(), cap)
            batch = []
            eldest = min(self._all_queued_locked(),
                         key=lambda r: r.enqueued_at)
            bound = max(8.0 * self.max_wait, self.STARVATION_S)
            if time.monotonic() - eldest.enqueued_at > bound:
                q = self._queues[eldest.tenant]
                q.remove((eldest.heap_key(), eldest))
                heapq.heapify(q)
                self._charge_locked(eldest.tenant)
                batch.append(eldest)
            while len(batch) < take:
                batch.append(self._pop_next_locked())
            self._deadlines -= sum(1 for r in batch
                                   if r.deadline is not None)
            self._inflight = len(batch)
        return batch

    def _run_batch(self, batch):
        n = len(batch)
        try:
            bucket = pick_bucket(n, self.buckets)
            inputs = {k: pad_to_bucket([r.inputs[k] for r in batch], bucket)
                      for k in batch[0].inputs}
            label = "serve %s batch n=%d bucket=%d" % (self.name, n, bucket)
            tic = time.monotonic()
            if self.watchdog is not None:
                with self.watchdog.armed(label):
                    faults.maybe_trip(SERVE_FORWARD_FAULT)
                    faults.maybe_hang(SERVE_FORWARD_HANG)
                    outs = self.runner(inputs, n)
            else:
                faults.maybe_trip(SERVE_FORWARD_FAULT)
                faults.maybe_hang(SERVE_FORWARD_HANG)
                outs = self.runner(inputs, n)
            dt = time.monotonic() - tic
        except Exception as e:  # noqa: BLE001 — every waiter must wake
            for r in batch:
                r.future.set_error(e)
            with self._cv:
                if not self._qtotal_locked():
                    # the pinned shapes may be the very thing that made
                    # this batch fail (a malformed first request) — let
                    # the next request after a drained queue re-pin
                    # rather than rejecting correct traffic forever
                    self._sample_shapes = None
            return
        self._ema_batch_s = dt if self._ema_batch_s is None \
            else 0.8 * self._ema_batch_s + 0.2 * dt
        if self.stats is not None:
            self.stats.record_batch(n, bucket, dt)
        now = time.monotonic()
        for i, r in enumerate(batch):
            r.future.set_result(
                [o[i] if np.ndim(o) and np.shape(o)[0] == bucket else o
                 for o in outs])
            if self.stats is not None:
                self.stats.record_latency(
                    (now - r.enqueued_at) * 1000.0,
                    tenant=r.tenant if r.tenant != DEFAULT_TENANT
                    else None)

    def run_exclusive(self, fn, timeout=30.0):
        """Run ``fn()`` at the DISPATCH BOUNDARY: wait for the in-flight
        batch to finish, keep the dispatcher from taking the next one
        while ``fn`` runs, then resume.  This is the serving hot-swap
        point (serving/deploy.py): the in-flight batch completes on the
        old weights, the batch after ``fn`` sees the new ones, and no
        queued request is dropped or errored — they just wait out
        ``fn``'s (milliseconds-scale) critical section.

        Raises :class:`MXNetError` when the in-flight batch does not
        finish within ``timeout`` (a wedged forward is the watchdog's
        job — the swap must not pile onto it)."""
        deadline = time.monotonic() + float(timeout)
        with self._cv:
            while self._paused:     # one exclusive section at a time
                if time.monotonic() >= deadline:
                    raise MXNetError(
                        "model %r: another exclusive section held the "
                        "dispatch boundary for %.1fs" % (self.name,
                                                         timeout))
                self._cv.wait(0.05)
            self._paused = True
            self._cv.notify_all()
            while self._inflight:
                if time.monotonic() >= deadline:
                    self._paused = False
                    self._cv.notify_all()
                    raise MXNetError(
                        "model %r: in-flight batch did not finish "
                        "within %.1fs — not swapping onto a wedged "
                        "forward" % (self.name, timeout))
                self._cv.wait(0.05)
        try:
            return fn()
        finally:
            with self._cv:
                self._paused = False
                self._cv.notify_all()

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain=True, timeout=30.0):
        """Stop the dispatcher.  ``drain=True`` refuses new submissions
        but finishes everything already queued/in flight first (the
        SIGTERM contract: no accepted request is dropped)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._draining = True
            if not drain:
                dropped = list(self._all_queued_locked())
                self._queues = {}
                self._deadlines = 0
            else:
                dropped = []
            self._cv.notify_all()
        for r in dropped:
            r.future.set_error(Draining("dropped: close(drain=False)"))
        with self._cv:
            while self._qtotal_locked() or self._inflight:
                if time.monotonic() >= deadline:
                    break
                self._cv.wait(0.1)
            self._closing = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
