"""mxserve: production inference serving on the predict/resilience stack
(docs/how_to/serving.md).

The reference stopped at a predict-only ABI (``c_predict_api.h`` ->
``mxnet_tpu/predict.py``); this package turns a trained checkpoint into
a traffic-serving daemon:

- :mod:`.batcher` — continuous request batching into padded power-of-two
  **bucket** shapes, one cached compiled forward per bucket.
- :mod:`.pool` — warm multi-model pool, device-resident weights,
  optional bf16 weight-cast, checkpoint-directory loading.
- :mod:`.frontend` — HTTP admission control: bounded queues, SLO-aware
  load shedding (429), ``/healthz`` + ``/stats``, graceful SIGTERM
  drain, StepWatchdog coverage of wedged forwards (exit 87 ->
  ``tools/supervise.py`` relaunch), weighted-fair tenant queueing.
- :mod:`.sequence` — bucketed SEQUENCE serving (``/predict_seq``):
  variable-length token streams length-bucketed at the front door, one
  batcher per (model, length) pair, answers trimmed to true length.

``tools/serve.py`` is the CLI daemon; ``bench.py``'s ``serve`` mode is
the load generator.
"""
from .batcher import (BucketBatcher, DeadlineExpired, Draining, QueueFull,
                      TenantQuotaExceeded, parse_buckets, pick_bucket,
                      pad_to_bucket, parse_tenant_weights)
from .pool import ModelPool, PooledModel
from .frontend import ServeClient, ServingFrontend, Stats
# deploy's MXTPU_SWAP_* knobs register EAGERLY here (the PR-7 lesson),
# and sequence's MXTPU_SERVE_SEQ_BUCKETS rides the same rule
from .deploy import CheckpointWatcher
from .sequence import (SequenceEntry, parse_seq_buckets, pick_seq_bucket,
                       seq_batcher_name)

__all__ = ["BucketBatcher", "DeadlineExpired", "Draining", "QueueFull",
           "TenantQuotaExceeded", "parse_buckets", "pick_bucket",
           "pad_to_bucket", "parse_tenant_weights", "ModelPool",
           "PooledModel", "ServeClient", "ServingFrontend", "Stats",
           "CheckpointWatcher", "SequenceEntry", "parse_seq_buckets",
           "pick_seq_bucket", "seq_batcher_name"]
