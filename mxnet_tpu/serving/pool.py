"""Warm multi-model pool: named models, device-resident weights, one
cached compiled forward per (model, bucket shape).

Load surfaces mirror the training side's artifacts:

- ``load(name, prefix, epoch)`` — the ``prefix-symbol.json`` +
  ``prefix-%04d.params`` pair every ``save_checkpoint`` writes.
- ``load_dir(name, directory)`` — a ``CheckpointManager`` directory:
  ``resilience.restore`` picks the newest INTACT epoch (checksum
  verification + corrupt-epoch walk-back included), so a serving daemon
  pointed at a live training run always comes up on good weights.
- ``add(name, symbol, arg_params, aux_params)`` — in-process handoff.

Weights stay device-resident inside each model's ``predict.Predictor``
(bound executors per bucket shape).  ``MXTPU_SERVE_DTYPE=bfloat16``
casts floating-point weights at load time (half the HBM + memory
bandwidth per forward; inputs stay f32 and XLA promotes), the classic
weight-cast serving mode.

``analyze()`` runs the mxlint graph rules over a bucket forward —
donation/dtype/callback/collective hygiene applies to inference graphs
too — and ``MXTPU_ANALYZE=1|strict`` lints each newly compiled bucket
before its first dispatch, exactly like the training-side gate.
"""
from __future__ import annotations

import threading

import numpy as np

from ..base import MXNetError, get_env, register_env

__all__ = ["ModelPool", "PooledModel", "ENV_SERVE_DTYPE"]

ENV_SERVE_DTYPE = register_env(
    "MXTPU_SERVE_DTYPE", default="float32",
    doc="Serving weight dtype: `bfloat16` casts floating-point weights "
        "at load time (weight-cast serving; inputs stay f32)")

_CASTABLE = ("float32", "float64")


class PooledModel(object):
    """One warm model: symbol + device-resident params + a Predictor
    whose per-shape executor cache holds one compiled forward per
    bucket.  All forwards are expected on ONE thread (the model's
    batcher dispatcher)."""

    def __init__(self, name, symbol, arg_params, aux_params=None,
                 dtype=None, ctx=None, sample_shapes=None):
        from .. import symbol as sym_mod
        self.name = name
        self.symbol = symbol if hasattr(symbol, "list_arguments") \
            else sym_mod.load_json(symbol)
        self.dtype = dtype if dtype is not None else get_env(ENV_SERVE_DTYPE)
        self.ctx = ctx
        self.arg_params = self._cast(arg_params or {})
        self.aux_params = self._cast(aux_params or {})
        #: {input_name: per-sample shape} once declared or first served
        self.sample_shapes = dict(sample_shapes) if sample_shapes else None
        self._pred = None
        self._cur_shapes = None
        self._analyzed = set()      # signatures that linted clean/warned
        self._refused = {}          # signature -> strict-mode message
        arg_names = self.symbol.list_arguments()
        #: data inputs = arguments with no loaded weight that aren't
        #: loss labels (labels are zero-filled by Predictor.reshape)
        self.input_names = [n for n in arg_names
                            if n not in self.arg_params
                            and not n.endswith("label")]
        self.output_names = self.symbol.list_outputs()

    def _cast(self, params):
        if self.dtype in (None, "", "float32"):
            return dict(params)
        out = {}
        for k, v in params.items():
            if np.dtype(v.dtype).name in _CASTABLE:
                out[k] = v.astype(self.dtype)
            else:
                out[k] = v
        return out

    def _blob(self):
        blob = {"arg:%s" % k: v for k, v in self.arg_params.items()}
        blob.update({"aux:%s" % k: v for k, v in self.aux_params.items()})
        return blob

    def forward(self, inputs, n_valid=None):
        """One batch forward at the given (bucket) shapes -> list of
        per-output numpy arrays.  Shapes repeat -> the Predictor's
        cached executor; a new shape compiles once (and is graph-linted
        when ``MXTPU_ANALYZE`` is set).  ``n_valid`` (how many leading
        rows are real vs padding) is accepted for batcher-runner
        compatibility; the whole padded batch always runs."""
        from .. import predict
        shapes = {k: tuple(np.shape(v)) for k, v in inputs.items()}
        new_sig = self._cur_shapes != shapes
        if new_sig:
            # gate BEFORE recording the signature: a strict-mode
            # refusal must stay sticky across retries, not be skipped
            # because the shape "already ran"
            self._maybe_env_analyze(shapes)
        if self._pred is None:
            self._pred = predict.Predictor(self.symbol, self._blob(),
                                           shapes, ctx=self.ctx)
        elif new_sig:
            self._pred.reshape(shapes)
        self._cur_shapes = shapes
        self._pred.forward(**inputs)
        if self.sample_shapes is None:
            # commit only AFTER a successful forward: a malformed first
            # request must never pin wrong shapes and brick the model
            # for every correct request that follows
            self.sample_shapes = {k: s[1:] for k, s in shapes.items()}
        return [self._pred.get_output(i)
                for i in range(len(self.output_names))]

    def warmup(self, buckets):
        """Compile (and fault in) one forward per bucket ahead of
        traffic.  Needs ``sample_shapes`` (declared at load time or via
        the first request)."""
        if self.sample_shapes is None:
            raise MXNetError(
                "model %r has no declared sample_shapes to warm up "
                "(pass sample_shapes= at load, or serve one request "
                "first)" % self.name)
        rs = np.random.RandomState(0)
        for b in buckets:
            dummy = {k: rs.rand(int(b), *s).astype(np.float32)
                     for k, s in self.sample_shapes.items()}
            self.forward(dummy)
        return self

    # -- static analysis ---------------------------------------------------
    def analyze(self, bucket=1):
        """mxlint graph lint of this model's bucket-``bucket`` forward
        (inference graphs obey the same donation/dtype/callback rules as
        training steps; a single-device forward must show NO
        collectives).  Returns the :class:`~..analysis.report.Report`."""
        import jax
        import jax.numpy as jnp
        from ..analysis import graph_lint
        from ..executor import _build_eval
        from ..ndarray import NDArray
        if self.sample_shapes is None:
            raise MXNetError("model %r: declare sample_shapes before "
                             "analyze()" % self.name)
        eval_fn = _build_eval(self.symbol)

        def _raw(d):
            return {k: (v._data if isinstance(v, NDArray)
                        else jnp.asarray(v)) for k, v in d.items()}

        params, auxs = _raw(self.arg_params), _raw(self.aux_params)
        shapes = {k: (int(bucket),) + tuple(s)
                  for k, s in self.sample_shapes.items()}
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        for n, shp in zip(self.symbol.list_arguments(), arg_shapes):
            if n not in params and n not in shapes:
                params[n] = jnp.zeros(shp, jnp.float32)
        for n, shp in zip(self.symbol.list_auxiliary_states(), aux_shapes):
            if n not in auxs:
                auxs[n] = jnp.zeros(shp, jnp.float32)
        input_names = sorted(shapes)
        rng = jax.random.PRNGKey(0)

        def infer(*inputs):
            merged = dict(params)
            merged.update(dict(zip(input_names, inputs)))
            outs, _ = eval_fn(merged, auxs, rng, False)
            return tuple(outs)

        rs = np.random.RandomState(0)
        args = [rs.rand(*shapes[n]).astype(np.float32)
                for n in input_names]
        return graph_lint.lint_jit(infer, *args, expect_allgather=False)

    def _maybe_env_analyze(self, shapes):
        """The ``MXTPU_ANALYZE`` gate, per newly compiled signature:
        warn (``1``) or refuse to serve (``strict``) on findings."""
        from ..analysis import ENV_ANALYZE
        mode = get_env(ENV_ANALYZE)
        if not mode:
            return
        sig = tuple(sorted(shapes.items()))
        if sig in self._refused:
            # a strict refusal is STICKY: a retry of the same signature
            # must not slip the violating program into service
            raise MXNetError(self._refused[sig])
        if sig in self._analyzed:
            return
        bucket = next(iter(shapes.values()))[0]
        report = self.analyze(bucket=bucket)
        if report.ok:
            self._analyzed.add(sig)
            _log().info("MXTPU_ANALYZE: serving forward %s@%s is clean",
                        self.name, bucket)
            return
        text = report.format_text()
        if str(mode).strip().lower() == "strict":
            msg = ("MXTPU_ANALYZE=strict: serving forward %s@%s has "
                   "findings:\n%s" % (self.name, bucket, text))
            self._refused[sig] = msg
            raise MXNetError(msg)
        self._analyzed.add(sig)
        _log().warning("MXTPU_ANALYZE: serving forward %s@%s has "
                       "findings:\n%s", self.name, bucket, text)


def _log():
    import logging
    return logging.getLogger(__name__)


class ModelPool(object):
    """Name -> :class:`PooledModel` registry (admin ops are locked; the
    per-model forward path is single-threaded by its batcher)."""

    def __init__(self, ctx=None, dtype=None):
        self.ctx = ctx
        self.dtype = dtype
        self._models = {}
        self._lock = threading.Lock()

    def _put(self, entry):
        with self._lock:
            self._models[entry.name] = entry
        return entry

    def add(self, name, symbol, arg_params=None, aux_params=None,
            sample_shapes=None, dtype=None):
        """Register an in-memory model."""
        return self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))

    def load(self, name, prefix, epoch=0, sample_shapes=None, dtype=None):
        """Load ``prefix-symbol.json`` + ``prefix-%04d.params`` (the
        ``save_checkpoint`` pair)."""
        from ..model import load_checkpoint
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))

    def load_dir(self, name, directory, epoch=None, sample_shapes=None,
                 dtype=None):
        """Load the newest intact epoch from a ``CheckpointManager``
        directory (checksum-verified, walk-back past corrupt epochs)."""
        from ..resilience import CheckpointManager
        man = CheckpointManager(directory)
        symbol, arg_params, aux_params, _states, ep = man.restore(epoch)
        if symbol is None:
            raise MXNetError(
                "checkpoint directory %r has no symbol file — serving "
                "needs the graph, not just params" % directory)
        entry = self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))
        entry.loaded_epoch = ep
        return entry

    def get(self, name):
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise MXNetError("no model %r in the pool (have: %s)"
                             % (name, self.names()))
        return entry

    def __contains__(self, name):
        with self._lock:
            return name in self._models

    def names(self):
        with self._lock:
            return sorted(self._models)

    def remove(self, name):
        with self._lock:
            self._models.pop(name, None)

    def warmup(self, buckets, names=None):
        """Warm every (or the named) model over ``buckets``."""
        for n in (self.names() if names is None else names):
            self.get(n).warmup(buckets)
        return self
