"""Warm multi-model pool: named models, device-resident weights, one
cached compiled forward per (model, bucket shape).

Load surfaces mirror the training side's artifacts:

- ``load(name, prefix, epoch)`` — the ``prefix-symbol.json`` +
  ``prefix-%04d.params`` pair every ``save_checkpoint`` writes.
- ``load_dir(name, directory)`` — a ``CheckpointManager`` directory:
  ``resilience.restore`` picks the newest INTACT epoch (checksum
  verification + corrupt-epoch walk-back included), so a serving daemon
  pointed at a live training run always comes up on good weights.
- ``add(name, symbol, arg_params, aux_params)`` — in-process handoff.

Weights stay device-resident inside each model's ``predict.Predictor``
(bound executors per bucket shape).  ``MXTPU_SERVE_DTYPE=bfloat16``
casts floating-point weights at load time (half the HBM + memory
bandwidth per forward; inputs stay f32 and XLA promotes), the classic
weight-cast serving mode.  ``MXTPU_SERVE_DTYPE=int8`` goes further:
dense/conv weights are quantized per OUTPUT CHANNEL with a symmetric
scale (``q = round(w / s)`` clipped to ±127, ``s = max|w| / 127`` over
the channel — ≤0.4% relative weight error by construction), the int8
tensors + f32 scales are what lives in device memory (~1/4 the bytes),
and dequantization ``q.astype(f32) * s`` happens INSIDE the compiled
forward right at the consuming matmul/conv, where XLA fuses it into
the dot operand — weight-only quantization, activations stay f32.
Non-eligible params (biases, BN stats, 1-D tensors) follow the same
cast path bfloat16 uses.  Accuracy contract: docs/how_to/serving.md.

``analyze()`` runs the mxlint graph rules over a bucket forward —
donation/dtype/callback/collective hygiene applies to inference graphs
too — and ``MXTPU_ANALYZE=1|strict`` lints each newly compiled bucket
before its first dispatch, exactly like the training-side gate.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from ..base import MXNetError, get_env, register_env

__all__ = ["ModelPool", "PooledModel", "ENV_SERVE_DTYPE"]

ENV_SERVE_DTYPE = register_env(
    "MXTPU_SERVE_DTYPE", default="float32",
    doc="Serving weight dtype: `bfloat16` casts floating-point weights "
        "at load time (weight-cast serving; inputs stay f32); `int8` "
        "quantizes dense/conv weights per output channel (symmetric "
        "scale, dequant inside the compiled forward at the matmul) — "
        "tolerance contract in docs/how_to/serving.md")

_CASTABLE = ("float32", "float64")


def quantize_int8(weight):
    """Per-output-channel symmetric int8 quantization of one weight.

    Axis 0 is the output channel for both FullyConnected ``(out, in)``
    and Convolution ``(out, in, kh, kw)`` weights (the reference
    layout).  Returns ``(q int8, scale f32)`` with ``scale`` shaped
    ``(out, 1, ...)`` so ``q * scale`` broadcasts back; an all-zero
    channel gets scale 1 (its q rows are zero anyway) so dequant never
    divides by zero."""
    w = np.asarray(weight.asnumpy() if hasattr(weight, "asnumpy")
                   else weight, dtype=np.float32)
    reduce_axes = tuple(range(1, w.ndim))
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale


def _int8_eligible(name, value):
    """Weight-only quantization targets: the 2-D+ ``*weight`` tensors
    (dense/conv kernels).  Biases, BN gamma/beta/moving stats and
    embeddings-as-1-D stay in float — they are small and scale-critical."""
    dt = np.dtype(getattr(value, "dtype", np.float32)).name
    return (name.endswith("weight") and dt in _CASTABLE
            and len(getattr(value, "shape", ())) >= 2)


class _Int8Forward(object):
    """The int8 serving executor: device-resident int8 weights + f32
    per-channel scales, one jitted forward per input-shape signature.
    The traced program's first ops dequantize each quantized weight
    (``q.astype(f32) * scale``) so XLA fuses the dequant straight into
    the consuming dot/conv operand — device memory holds the int8
    bytes, the f32 weight exists only as a fusion temp.  Mirrors
    ``predict.Predictor``'s per-shape program cache, so the bucket
    bit-exactness contract holds unchanged: one program per bucket
    shape, rows independent of fill/position/co-tenants."""

    def __init__(self, model):
        from ..executor import _build_eval

        self._sym = model.symbol
        self._eval = _build_eval(model.symbol)
        self._cache = {}            # shape signature -> jitted forward
        self.refresh(model)

    def refresh(self, model):
        """(Re-)stage ``model``'s current params on device — the int8
        half of the hot-swap path (``PooledModel.swap_params``).  The
        per-shape jitted cache survives a refresh: the compiled
        programs take q/scales/plain/aux as ARGUMENTS, so same-shaped
        new values hit the same program."""
        import jax.numpy as jnp
        from .aot import dev_array
        self._q, self._plain = {}, {}
        for k, v in model.arg_params.items():
            if k in model._wt_scales:
                self._q[k] = jnp.asarray(np.asarray(v))     # int8 bytes
            else:
                self._plain[k] = dev_array(v)
        self._scales = {k: jnp.asarray(s)
                        for k, s in model._wt_scales.items()}
        self._aux = {k: dev_array(v)
                     for k, v in model.aux_params.items()}
        return self

    def _build(self, shapes):
        import jax
        import jax.numpy as jnp
        from .aot import eval_closure, graph_fills
        # zero-fills AND the eval-closure body are shared with the AOT
        # exporter (serving/aot.py) — the two forward builders must
        # never drift on fill/rng/train-flag semantics; only the
        # in-graph dequant below is int8-specific
        fill, aux_fill = graph_fills(
            self._sym, shapes,
            set(self._q) | set(self._plain), self._aux)
        run = eval_closure(self._eval, fill, aux_fill, sorted(shapes))

        def infer(q, scales, plain, auxs, *inputs):
            merged = {k: q[k].astype(jnp.float32) * scales[k] for k in q}
            merged.update(plain)
            return run(merged, auxs, inputs)

        return jax.jit(infer)

    def forward(self, inputs, shapes):
        import jax.numpy as jnp
        sig = tuple(sorted(shapes.items()))
        fn = self._cache.get(sig)
        if fn is None:
            fn = self._build({k: tuple(v) for k, v in shapes.items()})
            self._cache[sig] = fn
        args = [jnp.asarray(np.asarray(inputs[n], dtype=np.float32))
                for n in sorted(shapes)]
        outs = fn(self._q, self._scales, self._plain, self._aux, *args)
        return [np.asarray(o) for o in outs]

    def resident_weight_bytes(self):
        """Device bytes held by the quantized weights (int8 + scales) —
        the observability hook the memory tests pin at ~1/4 of f32."""
        return sum(int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
                   for d in (self._q, self._scales) for v in d.values())


class PooledModel(object):
    """One warm model: symbol + device-resident params + a Predictor
    whose per-shape executor cache holds one compiled forward per
    bucket.  All forwards are expected on ONE thread (the model's
    batcher dispatcher)."""

    def __init__(self, name, symbol, arg_params, aux_params=None,
                 dtype=None, ctx=None, sample_shapes=None):
        from .. import symbol as sym_mod
        self.name = name
        self.symbol = symbol if hasattr(symbol, "list_arguments") \
            else sym_mod.load_json(symbol)
        self.dtype = dtype if dtype is not None else get_env(ENV_SERVE_DTYPE)
        self.ctx = ctx
        #: name -> per-channel scale for int8-quantized weights (empty
        #: for every other dtype; filled by ``_cast``)
        self._wt_scales = {}
        self._int8 = None
        #: bucket -> AOT-loaded compiled forward (serving/aot.py) and
        #: the device param/aux lists it is called with
        self._aot = {}
        self._aot_args = None
        self.arg_params = self._cast(arg_params or {})
        self.aux_params = self._cast(aux_params or {})
        #: checkpoint provenance: the epoch currently served (None for
        #: in-memory models) and, for directory loads, where new epochs
        #: appear — what CheckpointWatcher tails (serving/deploy.py)
        self.loaded_epoch = None
        self.source_dir = None
        self.source_prefix = "checkpoint"
        #: {input_name: per-sample shape} once declared or first served
        self.sample_shapes = dict(sample_shapes) if sample_shapes else None
        self._pred = None
        self._cur_shapes = None
        self._analyzed = set()      # signatures that linted clean/warned
        self._refused = {}          # signature -> strict-mode message
        arg_names = self.symbol.list_arguments()
        #: data inputs = arguments with no loaded weight that aren't
        #: loss labels (labels are zero-filled by Predictor.reshape)
        self.input_names = [n for n in arg_names
                            if n not in self.arg_params
                            and not n.endswith("label")]
        self.output_names = self.symbol.list_outputs()

    def _cast(self, params):
        if self.dtype in (None, "", "float32"):
            return dict(params)
        out = {}
        for k, v in params.items():
            if self.dtype == "int8":
                # weight-only quantization: dense/conv kernels go int8
                # per channel, everything else (biases, BN stats) rides
                # the float path unchanged — the bf16-compose rule
                if _int8_eligible(k, v):
                    q, s = quantize_int8(v)
                    out[k] = q
                    self._wt_scales[k] = s
                else:
                    out[k] = v
            elif np.dtype(v.dtype).name in _CASTABLE:
                out[k] = v.astype(self.dtype)
            else:
                out[k] = v
        return out

    def _blob(self):
        blob = {"arg:%s" % k: v for k, v in self.arg_params.items()}
        blob.update({"aux:%s" % k: v for k, v in self.aux_params.items()})
        return blob

    def forward(self, inputs, n_valid=None):
        """One batch forward at the given (bucket) shapes -> list of
        per-output numpy arrays.  Shapes repeat -> the Predictor's (or
        the int8 path's) cached executor; a new shape compiles once
        (and is graph-linted when ``MXTPU_ANALYZE`` is set).
        ``n_valid`` (how many leading rows are real vs padding) is
        accepted for batcher-runner compatibility; the whole padded
        batch always runs."""
        shapes = {k: tuple(np.shape(v)) for k, v in inputs.items()}
        new_sig = self._cur_shapes != shapes
        if new_sig:
            # gate BEFORE recording the signature: a strict-mode
            # refusal must stay sticky across retries, not be skipped
            # because the shape "already ran"
            self._maybe_env_analyze(shapes)
        aot_fn = self._aot_forward_for(shapes)
        if aot_fn is not None:
            import jax.numpy as jnp
            pv, av = self._aot_args
            xs = [jnp.asarray(np.asarray(inputs[n], dtype=np.float32))
                  for n in sorted(shapes)]
            self._cur_shapes = shapes
            outs = [np.asarray(o) for o in aot_fn(pv, av, *xs)]
        elif self._wt_scales:
            if self._int8 is None:
                self._int8 = _Int8Forward(self)
            self._cur_shapes = shapes
            outs = self._int8.forward(inputs, shapes)
        else:
            from .. import predict
            if self._pred is None:
                self._pred = predict.Predictor(self.symbol, self._blob(),
                                               shapes, ctx=self.ctx)
            elif new_sig:
                self._pred.reshape(shapes)
            self._cur_shapes = shapes
            self._pred.forward(**inputs)
            outs = [self._pred.get_output(i)
                    for i in range(len(self.output_names))]
        if self.sample_shapes is None:
            # commit only AFTER a successful forward: a malformed first
            # request must never pin wrong shapes and brick the model
            # for every correct request that follows
            self.sample_shapes = {k: s[1:] for k, s in shapes.items()}
        return outs

    def warmup(self, buckets):
        """Compile (and fault in) one forward per bucket ahead of
        traffic.  Needs ``sample_shapes`` (declared at load time or via
        the first request)."""
        if self.sample_shapes is None:
            raise MXNetError(
                "model %r has no declared sample_shapes to warm up "
                "(pass sample_shapes= at load, or serve one request "
                "first)" % self.name)
        rs = np.random.RandomState(0)
        for b in buckets:
            dummy = {k: rs.rand(int(b), *s).astype(np.float32)
                     for k, s in self.sample_shapes.items()}
            self.forward(dummy)
        return self

    # -- hot swap (serving/deploy.py; docs/how_to/serving.md) --------------
    @staticmethod
    def _param_sig(params):
        """{name: (shape, dtype)} — the program identity of a parameter
        set.  Two sets with equal signatures run the SAME cached
        compiled forwards; anything else is a different program."""
        return {k: (tuple(np.shape(v)),
                    np.dtype(getattr(v, "dtype", np.float32)).name)
                for k, v in params.items()}

    @staticmethod
    def _shelve(params):
        """A rollback-safe snapshot of a param dict: NDArray values get
        a FRESH shell around their (immutable) device buffer.  The
        Predictor swap path mutates the BOUND NDArrays' ``_data`` in
        place — without re-shelling, the snapshot would alias the very
        objects the swap overwrites and rollback would restore the new
        weights onto themselves."""
        from ..ndarray import NDArray
        return {k: (NDArray._from_jax(v._data)
                    if isinstance(v, NDArray) else v)
                for k, v in params.items()}

    def swap_params(self, arg_params, aux_params=None):
        """Hot-swap this model's device-resident weights to RAW
        checkpoint values (the pool's dtype cast / int8 quantization is
        re-applied here, exactly as at load).  Returns an opaque
        snapshot of the previous weights for :meth:`restore_params`.

        The caller owns the dispatch boundary: run this inside
        :meth:`BucketBatcher.run_exclusive` (``CheckpointWatcher``
        does) so no batch forward is in flight — the in-flight batch
        finishes on the old weights, the next batch sees the new ones.

        The parameter SET must be identical after the cast (names,
        shapes, dtypes): every cached compiled forward — Predictor
        executor, int8 program, AOT executable — is reused as-is, so a
        different set is a different program: a restart, not a swap."""
        snapshot = (self._shelve(self.arg_params),
                    self._shelve(self.aux_params),
                    dict(self._wt_scales))
        prev_scales = self._wt_scales
        self._wt_scales = {}
        try:
            new_args = self._cast(arg_params or {})
            new_auxs = self._cast(aux_params or {})
            if self._param_sig(new_args) != self._param_sig(snapshot[0]) \
                    or self._param_sig(new_auxs) != \
                    self._param_sig(snapshot[1]):
                raise MXNetError(
                    "model %r: the swapped-in parameter set does not "
                    "match the serving set (names/shapes/dtypes) — a "
                    "program change needs a reload, swaps only change "
                    "weights" % self.name)
            self._install(new_args, new_auxs, self._wt_scales)
        except Exception:
            self._wt_scales = prev_scales
            raise
        return snapshot

    def restore_params(self, snapshot):
        """Roll back to a :meth:`swap_params` snapshot (the post-swap
        probe-failed path)."""
        self._install(*snapshot)
        return self

    def _install(self, arg_params, aux_params, wt_scales):
        """Point every serving path at these (already-cast) params: the
        Predictor's bound executors in place, the int8 device stage,
        and the AOT call-time param lists."""
        self.arg_params = arg_params
        self.aux_params = aux_params
        self._wt_scales = wt_scales
        if self._pred is not None:
            self._pred.set_params(self._blob())
        if self._int8 is not None:
            self._int8.refresh(self)
        if self._aot_args is not None:
            from .aot import dev_array
            self._aot_args = (
                [dev_array(self.arg_params[n])
                 for n in sorted(self.arg_params)],
                [dev_array(self.aux_params[n])
                 for n in sorted(self.aux_params)])

    # -- AOT executable store (serving/aot.py; docs/how_to/fleet.md) -------
    def _aot_forward_for(self, shapes):
        """The loaded AOT executable matching these exact batch shapes,
        or None (Predictor/int8 path).  Key fact: one executable per
        bucket shape — the same program-identity discipline as the
        Predictor's per-shape cache, so the bit-stability contract is
        unchanged."""
        if not self._aot or self.sample_shapes is None:
            return None
        b = next(iter(shapes.values()))[0]
        fn = self._aot.get(b)
        if fn is None:
            return None
        want = {k: (b,) + tuple(s) for k, s in self.sample_shapes.items()}
        return fn if shapes == want else None

    def export_aot(self, buckets, store_dir):
        """Compile this model's forward for every bucket and serialize
        the executables into ``store_dir`` (the fleet warm-store build;
        weight-free artifacts — see serving/aot.py).  int8 pools keep
        their in-process path (the dequant program is rebuilt per
        process) — not exportable yet, documented in fleet.md."""
        from . import aot
        if self.sample_shapes is None:
            raise MXNetError("model %r: declare sample_shapes before "
                             "export_aot()" % self.name)
        if self._wt_scales:
            raise MXNetError("model %r: int8 pools cannot export AOT "
                             "artifacts (dequant program is built "
                             "in-process)" % self.name)
        store = aot.AotStore(store_dir)
        meta = aot.entry_meta(self)
        for b in buckets:
            compiled, args = aot.build_forward(
                self.symbol, self.arg_params, self.aux_params,
                self.sample_shapes, b)
            store.save(self.name, b, compiled, meta)
            if self._aot_args is None:
                self._aot_args = args
        return store

    def load_aot(self, store_dir, buckets=None):
        """Load this model's compiled forwards from an AOT store ->
        number of buckets loaded (0 = nothing usable: absent store,
        meta mismatch, foreign platform — the caller falls back to
        :meth:`warmup`).  One loaded program is validated with a real
        call so a corrupt store surfaces at bring-up, not first
        traffic."""
        from . import aot
        store = aot.AotStore(store_dir)
        if self._wt_scales:
            return 0                    # int8: in-process path only
        if store.verify(self.name, aot.entry_meta(self)) is None:
            return 0
        have = store.buckets(self.name)
        wanted = sorted(int(b) for b in buckets) if buckets else have
        loaded = {}
        for b in wanted:
            if b not in have:
                continue
            fn = store.load(self.name, b)
            if fn is None:
                continue
            loaded[b] = fn
        if not loaded:
            return 0
        if self._aot_args is None:
            from .aot import dev_array
            self._aot_args = (
                [dev_array(self.arg_params[n])
                 for n in sorted(self.arg_params)],
                [dev_array(self.aux_params[n])
                 for n in sorted(self.aux_params)])
        # fault-in + integrity: one real forward through the smallest
        # loaded bucket (an executable that cannot run must not serve)
        b0 = min(loaded)
        rs = np.random.RandomState(0)
        try:
            pv, av = self._aot_args
            xs = [np.asarray(rs.rand(b0, *self.sample_shapes[k]),
                             dtype=np.float32)
                  for k in sorted(self.sample_shapes)]
            outs = loaded[b0](pv, av, *xs)
            if np.shape(np.asarray(outs[0]))[0] != b0:
                raise MXNetError("wrong validation output shape")
        except Exception as e:  # noqa: BLE001 — stale/corrupt store
            _log().warning("AOT store %s: validation call failed for "
                           "%r (%s: %s) — falling back to trace warmup",
                           store_dir, self.name, type(e).__name__, e)
            self._aot_args = None
            return 0
        self._aot.update(loaded)
        return len(loaded)

    # -- static analysis ---------------------------------------------------
    def analyze(self, bucket=1):
        """mxlint graph lint of this model's bucket-``bucket`` forward
        (inference graphs obey the same donation/dtype/callback rules as
        training steps; a single-device forward must show NO
        collectives).  Returns the :class:`~..analysis.report.Report`."""
        import jax
        import jax.numpy as jnp
        from ..analysis import graph_lint
        from ..executor import _build_eval
        from ..ndarray import NDArray
        if self.sample_shapes is None:
            raise MXNetError("model %r: declare sample_shapes before "
                             "analyze()" % self.name)
        eval_fn = _build_eval(self.symbol)

        def _raw(d):
            return {k: (v._data if isinstance(v, NDArray)
                        else jnp.asarray(v)) for k, v in d.items()}

        params, auxs = _raw(self.arg_params), _raw(self.aux_params)
        for k, s in self._wt_scales.items():
            # the int8 path serves dequantized weights — lint the math
            # that actually runs, not the raw int8 bytes
            params[k] = params[k].astype(jnp.float32) * jnp.asarray(s)
        shapes = {k: (int(bucket),) + tuple(s)
                  for k, s in self.sample_shapes.items()}
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shapes)
        for n, shp in zip(self.symbol.list_arguments(), arg_shapes):
            if n not in params and n not in shapes:
                params[n] = jnp.zeros(shp, jnp.float32)
        for n, shp in zip(self.symbol.list_auxiliary_states(), aux_shapes):
            if n not in auxs:
                auxs[n] = jnp.zeros(shp, jnp.float32)
        input_names = sorted(shapes)
        rng = jax.random.PRNGKey(0)

        def infer(*inputs):
            merged = dict(params)
            merged.update(dict(zip(input_names, inputs)))
            outs, _ = eval_fn(merged, auxs, rng, False)
            return tuple(outs)

        rs = np.random.RandomState(0)
        args = [rs.rand(*shapes[n]).astype(np.float32)
                for n in input_names]
        report = graph_lint.lint_jit(infer, *args,
                                     expect_allgather=False)
        # plan-fusion-parity: the served graph's mxfuse rewrite (incl.
        # the bn_fold serving default and the inference-trace pruning)
        # must keep the plain-plan monitored path intact
        report.merge(graph_lint.audit_plan_fusion(self.symbol))
        return report

    def _maybe_env_analyze(self, shapes):
        """The ``MXTPU_ANALYZE`` gate, per newly compiled signature:
        warn (``1``) or refuse to serve (``strict``) on findings."""
        from ..analysis import ENV_ANALYZE
        mode = get_env(ENV_ANALYZE)
        if not mode:
            return
        sig = tuple(sorted(shapes.items()))
        if sig in self._refused:
            # a strict refusal is STICKY: a retry of the same signature
            # must not slip the violating program into service
            raise MXNetError(self._refused[sig])
        if sig in self._analyzed:
            return
        bucket = next(iter(shapes.values()))[0]
        report = self.analyze(bucket=bucket)
        if report.ok:
            self._analyzed.add(sig)
            _log().info("MXTPU_ANALYZE: serving forward %s@%s is clean",
                        self.name, bucket)
            return
        text = report.format_text()
        if str(mode).strip().lower() == "strict":
            msg = ("MXTPU_ANALYZE=strict: serving forward %s@%s has "
                   "findings:\n%s" % (self.name, bucket, text))
            self._refused[sig] = msg
            raise MXNetError(msg)
        self._analyzed.add(sig)
        _log().warning("MXTPU_ANALYZE: serving forward %s@%s has "
                       "findings:\n%s", self.name, bucket, text)


def _log():
    import logging
    return logging.getLogger(__name__)


class ModelPool(object):
    """Name -> :class:`PooledModel` registry (admin ops are locked; the
    per-model forward path is single-threaded by its batcher)."""

    def __init__(self, ctx=None, dtype=None):
        self.ctx = ctx
        self.dtype = dtype
        self._models = {}
        self._lock = threading.Lock()

    def _put(self, entry):
        with self._lock:
            self._models[entry.name] = entry
        return entry

    def add(self, name, symbol, arg_params=None, aux_params=None,
            sample_shapes=None, dtype=None):
        """Register an in-memory model."""
        return self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))

    def load(self, name, prefix, epoch=0, sample_shapes=None, dtype=None):
        """Load ``prefix-symbol.json`` + ``prefix-%04d.params`` (the
        ``save_checkpoint`` pair)."""
        from ..model import load_checkpoint
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        entry = self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))
        entry.loaded_epoch = int(epoch)
        return entry

    def load_dir(self, name, directory, epoch=None, sample_shapes=None,
                 dtype=None):
        """Load the newest intact epoch from a ``CheckpointManager``
        directory (checksum-verified, walk-back past corrupt epochs)."""
        from ..resilience import CheckpointManager
        man = CheckpointManager(directory)
        symbol, arg_params, aux_params, _states, ep = man.restore(epoch)
        if symbol is None:
            raise MXNetError(
                "checkpoint directory %r has no symbol file — serving "
                "needs the graph, not just params" % directory)
        entry = self._put(PooledModel(
            name, symbol, arg_params, aux_params,
            dtype=dtype if dtype is not None else self.dtype,
            ctx=self.ctx, sample_shapes=sample_shapes))
        entry.loaded_epoch = ep
        entry.source_dir = os.fspath(directory)
        entry.source_prefix = man.prefix
        return entry

    def get(self, name):
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise MXNetError("no model %r in the pool (have: %s)"
                             % (name, self.names()))
        return entry

    def __contains__(self, name):
        with self._lock:
            return name in self._models

    def names(self):
        with self._lock:
            return sorted(self._models)

    def remove(self, name):
        with self._lock:
            self._models.pop(name, None)

    def warmup(self, buckets, names=None):
        """Warm every (or the named) model over ``buckets``."""
        for n in (self.names() if names is None else names):
            self.get(n).warmup(buckets)
        return self
