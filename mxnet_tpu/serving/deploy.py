"""Train-to-serve hot swap: continuous deployment for a live pool
(docs/how_to/serving.md, "Continuous deployment").

A trainer's ``CheckpointManager`` directory is a stream of epochs; a
serving daemon pointed at it should FOLLOW that stream — without a
restart, without dropping a request, and without ever trusting bytes
the manifest's digests don't vouch for.  :class:`CheckpointWatcher` is
that seam, one model per watcher:

1. **Tail** the manifest (monotonic-clock poll; errors back the poll
   off exponentially) for an epoch newer than the one being served.
2. **Verify before reading** — :func:`~..resilience.verify_promotion`
   checks every file's size + digest against the manifest BEFORE any
   deserialization.  A damaged epoch is REJECTED (counted on
   ``/stats``) and the pool keeps serving the current epoch: the
   promote path never walks forward onto bad bytes, and never walks
   back either — rejection is not an invitation to guess.
3. **Stage + validate off the serving path** — the new params are
   loaded into a throwaway staged model, its shape/dtype/param-set
   digest must MATCH the serving model's (the ``serving/aot.py``
   meta-verify discipline: same program, new weights — anything else
   is a restart, not a swap), and one validation forward must produce
   finite outputs.
4. **Swap at the dispatch boundary** —
   :meth:`~.batcher.BucketBatcher.run_exclusive` parks the dispatcher
   between batches: the in-flight batch finishes on the old weights,
   the next batch sees the new ones, queued requests just wait out the
   milliseconds-long critical section.  ZERO requests are dropped or
   errored by a swap.
5. **Probe, then commit** — post-swap forwards through the REAL
   serving executors must come back finite; a failed probe rolls the
   previous weights back (``MXTPU_SWAP_ROLLBACK``) before any client
   request can reach them.

Bit-exactness contract (pinned in tests/test_serving.py): a model
whose weights did NOT change serves bitwise-identical outputs across
another model's swap, and a swapped model serves outputs bitwise equal
to a fresh pool loaded directly from the new checkpoint — the swap
installs the new epoch's exact bytes, not an approximation of them.

The fleet tier (``fleet/deploy.py``) rolls this one replica at a time;
``tools/ckpt_fsck.py --watch/--promote-gate`` reports with the same
verifier, so fsck and deploy can never drift on what "healthy" means.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..base import MXNetError, get_env, register_env
from ..resilience import CheckpointManager, faults, verify_promotion

__all__ = ["CheckpointWatcher", "SWAP_PROBE_FAULT",
           "ENV_SWAP_POLL_S", "ENV_SWAP_PROBES", "ENV_SWAP_ROLLBACK"]

ENV_SWAP_POLL_S = register_env(
    "MXTPU_SWAP_POLL_S", default=0.5,
    doc="CheckpointWatcher manifest-poll interval in seconds "
        "(monotonic clock; poll errors back off exponentially up to "
        "32x and reset on the next clean poll)")
ENV_SWAP_PROBES = register_env(
    "MXTPU_SWAP_PROBES", default=1,
    doc="Post-swap validation forwards through the serving executors "
        "before a hot swap commits; a non-finite (or failed) probe "
        "rolls the previous weights back")
ENV_SWAP_ROLLBACK = register_env(
    "MXTPU_SWAP_ROLLBACK", default=1,
    doc="0 disables automatic rollback on a failed post-swap probe "
        "(the swap then fails loudly and the model serves the new "
        "weights as-is — only for debugging a rollback itself)")

#: fault point on the post-swap probe (``faults.maybe_fail``): the
#: deterministic stand-in for weights that pass off-path validation but
#: break on the serving executors — the rollback drill's trigger
SWAP_PROBE_FAULT = "swap_probe"


def _log():
    import logging
    return logging.getLogger(__name__)


def _finite(outputs):
    return all(np.isfinite(np.asarray(o)).all() for o in outputs)


class CheckpointWatcher(object):
    """Tail one model's checkpoint directory and hot-swap verified new
    epochs into the live pool (see the module docstring for the
    promote pipeline).

    ``frontend`` (a :class:`~.frontend.ServingFrontend`) supplies the
    model's batcher so the swap lands at the dispatch boundary under
    real traffic; without one (bare-pool tests, offline promotion) the
    swap runs directly — the caller then owns the forward path.

    Thread-safe: the poll thread and the ``/swap`` admin endpoint both
    funnel through one lock, so at most one promotion is in flight per
    model.
    """

    #: error-poll backoff cap, in multiples of ``poll_s``
    MAX_BACKOFF_X = 32.0

    def __init__(self, pool, model, directory=None, prefix=None,
                 frontend=None, poll_s=None, probes=None, rollback=None):
        entry = pool.get(model)
        self.pool = pool
        self.model = model
        self.frontend = frontend
        self.directory = directory or entry.source_dir
        if not self.directory:
            raise MXNetError(
                "model %r was not loaded from a checkpoint directory — "
                "nothing to watch (load it with ModelPool.load_dir, or "
                "pass directory=)" % model)
        self.prefix = prefix or entry.source_prefix or "checkpoint"
        self.poll_s = float(get_env(ENV_SWAP_POLL_S)
                            if poll_s is None else poll_s)
        self.probes = max(1, int(get_env(ENV_SWAP_PROBES)
                                 if probes is None else probes))
        self.rollback = bool(int(get_env(ENV_SWAP_ROLLBACK))
                             if rollback is None else rollback)
        self._man = CheckpointManager(self.directory, prefix=self.prefix,
                                      keep_last=None)
        self.counters = {"polls": 0, "promoted": 0, "rejected": 0,
                         "validation_failures": 0, "rolled_back": 0,
                         "swap_errors": 0}
        self.last_swap_ms = None
        #: publish->served latency of the LAST promote: wall-clock span
        #: from the manifest entry's publish timestamp (written by the
        #: trainer's CheckpointManager.save) to the moment the epoch
        #: went live here — the region drill's end-to-end freshness
        #: metric (docs/how_to/region.md)
        self.last_freshness_ms = None
        self.last_outcome = None
        #: bad publishes already counted: epoch -> manifest-entry mark,
        #: so one rotted epoch is one ``rejected``, not one per poll —
        #: a REWRITTEN epoch (new entry) is re-verified
        self._rejected_marks = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- observation -------------------------------------------------------
    def watching(self):
        return self._thread is not None and self._thread.is_alive()

    def stats(self):
        """The ``/stats`` deploy block for this model.  Deliberately
        LOCK-FREE: ``check_once`` holds the promote lock for a whole
        promotion (staging can include an XLA compile), and a /stats
        poll that blocked on it would make the fleet router's probe
        time out and count the replica as down mid-promotion.  The
        counters are GIL-atomic dict reads — a snapshot taken mid-swap
        may be one increment stale, never torn."""
        out = {"model": self.model, "directory": self.directory,
               "epoch": self.pool.get(self.model).loaded_epoch,
               "watching": self.watching(), "poll_s": self.poll_s,
               "last_swap_ms": self.last_swap_ms,
               "last_freshness_ms": self.last_freshness_ms,
               "last_outcome": self.last_outcome}
        out.update(self.counters)
        return out

    # -- the promote pipeline ----------------------------------------------
    def check_once(self, epoch=None, force=False):
        """One poll: verify the newest (or the given) epoch and promote
        it when it is newer than the served one and fully healthy.
        ``force=True`` (what an explicit ``/swap`` sends) re-attempts a
        publish the poll loop is holding after an earlier failure.
        Returns the outcome dict (``ok``, ``action``, ``epoch`` —
        JSON-safe; also stored as ``last_outcome``)."""
        with self._lock:
            return self._check_locked(epoch, force=force)

    def _entry_mark(self, epoch):
        """Identity of one manifest publish (resilience.publish_mark —
        shared with the fleet rollout): a rewritten epoch gets
        re-verified, an unchanged bad one is not re-counted per poll."""
        from ..resilience import publish_mark
        return publish_mark(self.directory, epoch, prefix=self.prefix)

    def _outcome(self, ok, action, **extra):
        out = {"ok": bool(ok), "action": action, "model": self.model}
        out.update(extra)
        self.last_outcome = out
        return out

    def _check_locked(self, target, force=False):
        self.counters["polls"] += 1
        entry = self.pool.get(self.model)
        current = entry.loaded_epoch
        epoch, problems = verify_promotion(self.directory, epoch=target,
                                           prefix=self.prefix)
        if epoch is None:
            return self._outcome(False, "no_checkpoint",
                                 problems=problems, epoch=current)
        if target is None and current is not None and epoch <= current:
            return self._outcome(True, "current", epoch=current)
        if problems:
            mark = self._entry_mark(epoch)
            if target is None and not force and \
                    self._rejected_marks.get(epoch) == mark:
                # this exact bad publish was already counted — stay
                # quiet until it changes or a newer epoch appears
                return self._outcome(False, "rejected", epoch=current,
                                     target=epoch, problems=problems,
                                     already_counted=True)
            self._rejected_marks[epoch] = mark
            self.counters["rejected"] += 1
            _log().warning(
                "CheckpointWatcher[%s]: REJECTING epoch %d — verification "
                "failed, keeping epoch %s live: %s", self.model, epoch,
                current, "; ".join(problems))
            return self._outcome(False, "rejected", epoch=current,
                                 target=epoch, problems=problems)
        mark = self._entry_mark(epoch)
        if target is None and not force and \
                self._rejected_marks.get(epoch) == mark:
            # this publish already failed validation/probe: do not
            # re-stage (and re-pause dispatch) every poll — hold until
            # the epoch is rewritten, a newer one appears, or an
            # explicit /swap (force=True) retries it
            return self._outcome(False, "held", epoch=current,
                                 target=epoch, already_counted=True)
        return self._promote(entry, epoch, current, mark)

    def _load_raw(self, epoch):
        """The new epoch's RAW param bytes (digest-verified upstream),
        split into (arg_params, aux_params)."""
        from .. import ndarray as nd
        entry = self._man.entry(epoch) or {}
        if entry.get("shard_set"):
            # sharded-native publish: assemble from the per-shard blobs
            # (shard-set completeness + every digest re-verified before
            # a byte deserializes — same walk-back-grade guarantees)
            args, auxs, _states = self._man._restore_sharded(epoch,
                                                             entry)
            return args, auxs
        raw = nd.load(self._man.params_path(epoch))
        args = {k[4:]: v for k, v in raw.items() if k.startswith("arg:")}
        auxs = {k[4:]: v for k, v in raw.items() if k.startswith("aux:")}
        return args, auxs

    def _probe_inputs(self, entry):
        rs = np.random.RandomState(0)
        return {k: rs.rand(1, *s).astype(np.float32)
                for k, s in entry.sample_shapes.items()}

    def _serving_probe_inputs(self, entry):
        """Post-swap probe inputs at the LAST-SERVED signature when one
        exists: that program is already compiled, so the probe can
        never drag an XLA compile into the paused-dispatcher critical
        section (the milliseconds-scale contract).  A never-served
        model probes at bucket 1 — there is no traffic to stall."""
        shapes = entry._cur_shapes
        if not shapes:
            return self._probe_inputs(entry)
        rs = np.random.RandomState(0)
        return {k: rs.rand(*s).astype(np.float32)
                for k, s in shapes.items()}

    def _promote(self, entry, epoch, current, mark=None):
        from . import aot
        from .pool import PooledModel
        if mark is not None:
            # any failure below marks this publish as tried — the poll
            # loop holds instead of re-staging it forever; a success
            # clears the mark
            self._rejected_marks[epoch] = mark
        if entry.sample_shapes is None:
            self.counters["validation_failures"] += 1
            return self._outcome(
                False, "validation_failed", epoch=current, target=epoch,
                problems=["model %r has no declared sample_shapes — the "
                          "pre-swap validation forward needs them"
                          % self.model])
        # -- stage + validate OFF the serving path -------------------------
        try:
            # inside the guard: between verification and this read the
            # trainer may have re-written (or retention pruned) the
            # epoch — that is a rejection, not a watcher crash
            args, auxs = self._load_raw(epoch)
            staged = PooledModel(entry.name, entry.symbol, args, auxs,
                                 dtype=entry.dtype, ctx=entry.ctx,
                                 sample_shapes=entry.sample_shapes)
            if aot.entry_meta(staged) != aot.entry_meta(entry):
                raise MXNetError(
                    "epoch %d's parameter set/shapes/dtype do not match "
                    "the serving program — a graph change needs a "
                    "restart, not a swap" % epoch)
            outs = staged.forward(self._probe_inputs(entry))
            if not _finite(outs):
                raise MXNetError("epoch %d's validation forward produced "
                                 "non-finite outputs" % epoch)
        except Exception as e:  # noqa: BLE001 — any staging failure
            self.counters["validation_failures"] += 1
            _log().warning(
                "CheckpointWatcher[%s]: epoch %d failed staged "
                "validation (%s: %s) — keeping epoch %s live",
                self.model, epoch, type(e).__name__, e, current)
            return self._outcome(False, "validation_failed",
                                 epoch=current, target=epoch,
                                 problems=["%s: %s"
                                           % (type(e).__name__, e)])
        # -- swap at the dispatch boundary, probe, commit ------------------
        probe_x = self._serving_probe_inputs(entry)

        def _swap_and_probe():
            snap = entry.swap_params(args, auxs)
            try:
                for _ in range(self.probes):
                    faults.maybe_fail(SWAP_PROBE_FAULT)
                    if not _finite(entry.forward(dict(probe_x))):
                        raise MXNetError("non-finite post-swap probe "
                                         "output")
            except Exception:
                if self.rollback:
                    entry.restore_params(snap)
                raise
            return snap

        batcher = None
        if self.frontend is not None:
            batcher = self.frontend.batcher(self.model, entry=entry)
        tic = time.monotonic()
        try:
            if batcher is not None:
                batcher.run_exclusive(_swap_and_probe)
            else:
                _swap_and_probe()
        except Exception as e:  # noqa: BLE001 — probe/boundary failure
            if self.rollback:
                self.counters["rolled_back"] += 1
                action = "rolled_back"
            else:
                self.counters["swap_errors"] += 1
                action = "swap_failed"
            _log().warning(
                "CheckpointWatcher[%s]: swap to epoch %d failed (%s: "
                "%s)%s", self.model, epoch, type(e).__name__, e,
                " — previous weights restored" if self.rollback else "")
            return self._outcome(False, action, epoch=current,
                                 target=epoch,
                                 problems=["%s: %s"
                                           % (type(e).__name__, e)])
        swap_ms = (time.monotonic() - tic) * 1e3
        entry.loaded_epoch = epoch
        self._rejected_marks.pop(epoch, None)
        self.counters["promoted"] += 1
        self.last_swap_ms = round(swap_ms, 3)
        try:
            published = (self._man.entry(epoch) or {}).get("time")
        except Exception:  # noqa: BLE001 — provenance is best-effort
            published = None
        if published is not None:
            # wall clock on both sides (publisher + server): the two
            # processes may be different hosts, and time.time() is the
            # only shared clock the manifest can carry
            self.last_freshness_ms = round(
                max(0.0, time.time() - float(published)) * 1e3, 3)
        _log().info("CheckpointWatcher[%s]: hot-swapped epoch %s -> %d "
                    "in %.1fms", self.model, current, epoch, swap_ms)
        return self._outcome(True, "promoted", epoch=epoch,
                             from_epoch=current,
                             swap_ms=self.last_swap_ms,
                             freshness_ms=self.last_freshness_ms)

    # -- the poll thread ---------------------------------------------------
    def start(self):
        """Start tailing the directory (idempotent); returns self."""
        if self.watching():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mxswap-%s" % self.model, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _loop(self):
        delay = self.poll_s
        while not self._stop.wait(delay):
            try:
                self.check_once()
                delay = self.poll_s
            except Exception as e:  # noqa: BLE001 — the tail must live
                # an unreadable directory (NFS blip, mid-copy manifest)
                # must not spin the poll hot OR kill the watcher: back
                # off on the monotonic clock, reset on the next clean
                # poll
                delay = min(delay * 2.0,
                            self.poll_s * self.MAX_BACKOFF_X)
                _log().warning(
                    "CheckpointWatcher[%s]: poll failed (%s: %s) — "
                    "backing off to %.1fs", self.model,
                    type(e).__name__, e, delay)
