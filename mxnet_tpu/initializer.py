"""Weight initializers (reference python/mxnet/initializer.py, 501 LoC).

Name-pattern dispatch is preserved: *_bias→zero, *_gamma→one, *_beta→zero,
*_moving_mean→zero, *_moving_var→one, *_weight→_init_weight, and attribute
overrides via ``__init__`` symbol attrs.
"""
from __future__ import annotations

import json
import logging
import re

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray, array as nd_array
from . import random as _random

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "One", "Zero", "Constant", "Load",
           "Mixed", "InitDesc", "register"]

init_registry = Registry("initializer")
register = init_registry.register


class InitDesc(str):
    """Name + attrs descriptor (later-reference compat)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer(object):
    """Base initializer: dispatch on parameter name (reference
    initializer.py:Initializer.__call__)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, name, arr):
        if not isinstance(name, str):
            raise TypeError("name must be a string")
        if not isinstance(arr, NDArray):
            raise TypeError("arr must be NDArray")
        if isinstance(name, InitDesc) and name.attrs.get("__init__"):
            klass, kwargs = json.loads(name.attrs["__init__"])
            init_registry.create(klass, **kwargs)._init_weight(name, arr)
            return
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("parameters"):
            self._init_rnn_parameters(name, arr)
        elif "init_h" in name or "init_c" in name or "begin_state" in name:
            self._init_zero(name, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype="float32")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_rnn_parameters(self, name, arr):
        """Fused-RNN packed 1-D parameter vectors: apply the subclass's
        weight rule when it handles vectors (Zero/Constant/Uniform/Normal);
        matrix-shaped inits (Xavier/Orthogonal) fall back to the classic
        small-uniform RNN init.  Use initializer.FusedRNN for exact
        per-gate-matrix initialization (reference initializer.py FusedRNN)."""
        try:
            self._init_weight(name, arr)
        except ValueError:
            _random.uniform(-0.07, 0.07, out=arr, shape=arr.shape)

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" (1.0), and "
            "\"beta\" (0.0)." % name)


@register
class Load(object):
    """Initialize from a dict of arrays, fall back to ``default_init``
    (reference initializer.py:Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        qualified = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                qualified[name[4:]] = arr
            else:
                qualified[name] = arr
        self.param = qualified
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise MXNetError(
                    "Parameter %s cannot be initialized from loading. "
                    "Shape mismatch, target %s vs loaded %s"
                    % (name, arr.shape, self.param[name].shape))
            arr[:] = self.param[name]
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise MXNetError(
                    "Cannot Initialize %s. Not found in loaded param and no "
                    "default Initializer is provided." % name)
            self.default_init(name, arr)


@register
class Mixed(object):
    """Pattern-dispatched mix of initializers (reference initializer.py:Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern" % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference initializer.py:Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        _random.uniform(-self.scale, self.scale, out=arr, shape=arr.shape)


@register
class Normal(Initializer):
    """N(0, sigma) (reference initializer.py:Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        _random.normal(0, self.sigma, out=arr, shape=arr.shape)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (reference initializer.py:Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _v, q = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else q
        arr[:] = (self.scale * res).reshape(arr.shape)


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py:Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(
                "Xavier initializer cannot be applied to vector %s. It requires"
                " at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            _random.uniform(-scale, scale, out=arr, shape=arr.shape)
        elif self.rnd_type == "gaussian":
            _random.normal(0, scale, out=arr, shape=arr.shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """MSRA/He init (reference initializer.py:MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Initialize LSTM stacked bias [i,f,c,o] with the forget gate set to
    ``forget_bias`` and the rest zero (reference initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = int(arr.shape[0] / 4)
        arr[num_hidden:2 * num_hidden] = self.forget_bias

    _init_bias = _init_weight


class FusedRNN(Initializer):
    """Initialize fused-RNN packed parameter vectors by unpacking into
    per-layer gate matrices, applying an inner initializer, and re-packing
    (reference initializer.py FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_rnn_parameters(self, name, arr):
        self._init_weight(name, arr)

    def _init_weight(self, name, arr):
        from .ops.nn import _RNN_GATES
        gates = _RNN_GATES[self._mode]
        dirs = 2 if self._bidirectional else 1
        h = self._num_hidden
        flat = np.zeros(arr.size, dtype="float32")
        # solve input size from total (see rnn_param_size)
        rest = arr.size - (self._num_layers - 1) * dirs * gates * h * \
            (dirs * h + h + 2)
        in_size = rest // (dirs * gates * h) - h - 2
        p = 0
        for layer in range(self._num_layers):
            li = in_size if layer == 0 else h * dirs
            for _d in range(dirs):
                for kind_cols in (li, h):
                    w = nd_zeros_like_np((gates * h, kind_cols))
                    self._init("weight", w)
                    flat[p:p + w.size] = w.asnumpy().reshape(-1)
                    p += w.size
        for layer in range(self._num_layers):
            for _d in range(dirs):
                for _kind in range(2):
                    b = nd_zeros_like_np((gates * h,))
                    if self._mode == "lstm":
                        LSTMBias(self._forget_bias)._init_bias("bias", b)
                    flat[p:p + b.size] = b.asnumpy().reshape(-1)
                    p += b.size
        arr[:] = flat


def nd_zeros_like_np(shape):
    from .ndarray import zeros
    return zeros(shape)
