"""Custom operators written in Python (reference python/mxnet/operator.py).

The reference routes Python callbacks through the C API (`MXCustomOpRegister`,
src/operator/custom/custom.cc) so they run engine-safely inside the threaded
executor.  Here the same surface — ``CustomOp``/``CustomOpProp`` +
``mx.operator.register`` and the legacy ``NumpyOp``/``NDArrayOp`` — lowers to
``jax.pure_callback`` (host callback with declared result shapes, the XLA
analog of the engine-safe callback) wrapped in ``jax.custom_vjp`` so the
user's ``backward`` defines the gradient.  Custom ops therefore work in BOTH
the imperative path and inside jit-compiled executor graphs.

Usage (identical to the reference)::

    @mx.operator.register("mysigmoid")
    class MySigmoidProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)
        def list_arguments(self): return ['data']
        def list_outputs(self): return ['output']
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes): return MySigmoid()

    y = mx.sym.Custom(x, op_type='mysigmoid')
    y = mx.nd.Custom(x_nd, op_type='mysigmoid')
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import register as _register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop",
           "NumpyOp", "NDArrayOp"]


def _assign(dst, req, src):
    """Write src into dst honoring the grad_req (reference operator.py
    CustomOp.assign semantics, shared by all op base classes)."""
    if req == "null":
        return
    if not isinstance(src, np.ndarray) and hasattr(src, "asnumpy"):
        # an NDArray built inside the callback: pull it host-side once
        # here rather than letting numpy's setitem trigger __array__
        src = src.asnumpy()
    if req in ("write", "inplace"):
        dst[:] = src
    elif req == "add":
        dst[:] += src

# op_type -> CustomOpProp subclass (reference CustomOpProp registry,
# src/operator/custom/custom.cc CustomOpPropRegistry)
_PROP_REGISTRY = {}


class CustomOp(object):
    """Base class for custom operator implementations (reference
    operator.py:CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        """Write src to dst honoring the grad_req (operator.py:assign)."""
        _assign(dst, req, src)


class CustomOpProp(object):
    """Operator properties: shapes, types, and operator creation (reference
    operator.py:CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type=reg_name``
    (reference operator.py:register / MXCustomOpRegister)."""
    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("can only register subclass of CustomOpProp")
        _PROP_REGISTRY[reg_name] = prop_cls
        _cached_prop.cache_clear()  # re-registration must not serve stale props
        return prop_cls
    return do_register


def get_prop(op_type):
    try:
        return _PROP_REGISTRY[op_type]
    except KeyError:
        raise MXNetError("custom op type %r is not registered "
                         "(use mx.operator.register)" % (op_type,)) from None


def _user_attrs(attrs):
    """kwargs forwarded to the user's prop ctor, as strings (the reference
    passes all op kwargs through the C API as char**)."""
    return {k: str(v) for k, v in attrs.items()
            if k != "op_type" and not k.startswith("__")}


@functools.lru_cache(maxsize=512)
def _cached_prop(op_type, attr_items):
    return get_prop(op_type)(**dict(attr_items))


def _prop_for(attrs):
    op_type = attrs.get("op_type")
    if op_type is None:
        raise MXNetError("Custom op requires op_type=...")
    items = tuple(sorted(_user_attrs(attrs).items()))
    return _cached_prop(op_type, items)


def _create_operator(op_type, attr_items, shapes, dtypes):
    """A fresh stateful operator per Custom-node instantiation: under a
    per-executor jit trace this yields one instance per bound executor
    (matching the reference, custom-inl.h CreateOperator); imperatively the
    forward/backward pair still shares the instance via the vjp closures."""
    prop = _cached_prop(op_type, attr_items)
    return prop.create_operator("tpu(0)", [list(s) for s in shapes],
                                [np.dtype(d).name for d in dtypes])


class _HostArray(np.ndarray):
    """What custom-op callbacks receive: a numpy view with the NDArray
    conveniences (.asnumpy/.wait_to_read/.copyto/.context).

    Callbacks run on a runtime callback thread while the compiled
    program that invoked them is still executing — creating device
    arrays there (the old path device_put every input) can deadlock
    against the main thread's device_get (observed with a CustomOp
    inside a fit loop).  The reference hands CPU NDArrays; a numpy view
    is the TPU-native equivalent: zero-copy, full numpy operator
    surface, and no device traffic from inside a callback."""

    def asnumpy(self):
        return np.asarray(self)

    def wait_to_read(self):
        pass

    wait_to_write = wait_to_read

    def copyto(self, other):
        other[:] = self
        return other

    @property
    def context(self):
        from .context import cpu
        return cpu()


def _wrap_nd(arrays):
    out = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        if not a.flags.writeable:
            # np.asarray(jax.Array) aliases jax's read-only host cache;
            # callbacks write in-place (out/aux/in-grad buffers, and some
            # user ops scribble on inputs) — give them their own copy,
            # which is what the old device-NDArray path did implicitly
            a = a.copy()
        out.append(a.view(_HostArray))
    return out


def _custom_input_names(attrs):
    prop = _prop_for(attrs)
    return tuple(prop.list_arguments())


def _custom_aux_names(attrs):
    prop = _prop_for(attrs)
    return tuple(prop.list_auxiliary_states())


def _custom_num_outputs(attrs):
    return len(_prop_for(attrs).list_outputs())


def _custom_output_names(attrs):
    return tuple(_prop_for(attrs).list_outputs())


def _custom_infer_shape(attrs, in_shapes):
    prop = _prop_for(attrs)
    n_out = len(prop.list_outputs())
    if all(s is None for s in in_shapes):
        return list(in_shapes), [None] * n_out, []
    if any(s is None for s in in_shapes):
        # partial knowledge: the prop may be able to fill the rest (e.g.
        # weight shapes derived from data, the reference's standard
        # simple_bind flow); props that need every input just bail
        try:
            ret = prop.infer_shape([list(s) if s is not None else None
                                    for s in in_shapes])
        except (TypeError, IndexError, AttributeError):
            return list(in_shapes), [None] * n_out, []
        if len(ret) == 2:
            in_sh, out_sh = ret
            aux_sh = []
        else:
            in_sh, out_sh, aux_sh = ret
        return ([tuple(s) if s is not None else None for s in in_sh],
                [tuple(s) if s is not None else None for s in out_sh],
                [tuple(s) for s in aux_sh])
    ret = prop.infer_shape([list(s) for s in in_shapes])
    if len(ret) == 2:
        in_sh, out_sh = ret
        aux_sh = []
    else:
        in_sh, out_sh, aux_sh = ret
    return ([tuple(s) for s in in_sh], [tuple(s) for s in out_sh],
            [tuple(s) for s in aux_sh])


@_register_op("Custom", input_names=_custom_input_names,
              aux_names=_custom_aux_names, num_outputs=_custom_num_outputs,
              output_names=_custom_output_names,
              infer_shape=_custom_infer_shape, needs_is_train=True,
              no_jit=True)
def _custom(*inputs, is_train=False, **attrs):
    """Python CustomOp node (reference src/operator/custom/custom.cc) —
    host callback via jax.pure_callback, gradient via jax.custom_vjp."""
    prop = _prop_for(attrs)
    arg_names = prop.list_arguments()
    aux_names = prop.list_auxiliary_states()
    n_in, n_aux = len(arg_names), len(aux_names)
    n_out = len(prop.list_outputs())
    data_in, aux_in = inputs[:n_in], inputs[n_in:n_in + n_aux]

    in_shapes = tuple(tuple(x.shape) for x in data_in)
    _, out_shapes, _ = _custom_infer_shape(attrs, in_shapes)
    in_types = [np.dtype(x.dtype) for x in data_in]
    _, out_types, _ = prop.infer_type(in_types)
    op = _create_operator(attrs["op_type"],
                          tuple(sorted(_user_attrs(attrs).items())),
                          in_shapes, tuple(in_types))

    out_structs = tuple(jax.ShapeDtypeStruct(s, np.dtype(t))
                        for s, t in zip(out_shapes, out_types))
    aux_structs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in aux_in)

    def host_forward(*arrs):
        ins = _wrap_nd(arrs[:n_in])
        auxs = _wrap_nd(arrs[n_in:])
        outs = _wrap_nd([np.zeros(s, t) for s, t in
                         zip(out_shapes, out_types)])
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=ins, out_data=outs, aux=auxs)
        return tuple(o.asnumpy() for o in outs) + \
            tuple(a.asnumpy() for a in auxs)

    def host_backward(*arrs):
        k = 0
        ograds = _wrap_nd(arrs[k:k + n_out]); k += n_out
        ins = _wrap_nd(arrs[k:k + n_in]); k += n_in
        outs = _wrap_nd(arrs[k:k + n_out]); k += n_out
        auxs = _wrap_nd(arrs[k:])
        igrads = _wrap_nd([np.zeros(s, t) for s, t in
                           zip(in_shapes, in_types)])
        op.backward(req=["write"] * n_in, out_grad=ograds, in_data=ins,
                    out_data=outs, in_grad=igrads, aux=auxs)
        return tuple(g.asnumpy() for g in igrads)

    in_structs = tuple(jax.ShapeDtypeStruct(s, t)
                       for s, t in zip(in_shapes, in_types))

    def _all_concrete(*xs):
        return not any(isinstance(x, jax.core.Tracer) for x in xs)

    @jax.custom_vjp
    def run(data_in, aux_in):
        if _all_concrete(*data_in, *aux_in):
            # concrete values: call the host fn directly — some TPU PJRT
            # backends (axon) reject the callback primitive outright
            res = host_forward(*[np.asarray(x) for x in data_in],
                               *[np.asarray(x) for x in aux_in])
            return tuple(jnp.asarray(r) for r in res)
        res = jax.pure_callback(host_forward, out_structs + aux_structs,
                                *data_in, *aux_in)
        return tuple(res)

    def run_fwd(data_in, aux_in):
        res = run(data_in, aux_in)
        return res, (data_in, aux_in, res[:n_out])

    def run_bwd(saved, cts):
        data_in_, aux_in_, outs = saved
        ograds = cts[:n_out]
        if _all_concrete(*ograds, *data_in_, *outs, *aux_in_):
            igrads = host_backward(
                *[np.asarray(x) for x in ograds],
                *[np.asarray(x) for x in data_in_],
                *[np.asarray(x) for x in outs],
                *[np.asarray(x) for x in aux_in_])
            igrads = tuple(jnp.asarray(g) for g in igrads)
        else:
            igrads = jax.pure_callback(host_backward, in_structs,
                                       *ograds, *data_in_, *outs, *aux_in_)
            igrads = tuple(igrads)
        # integer/bool primals take symbolic-zero (float0) cotangents
        fixed = []
        for g, x in zip(igrads, data_in_):
            if jnp.issubdtype(x.dtype, jnp.floating) or \
                    jnp.issubdtype(x.dtype, jnp.complexfloating):
                fixed.append(g)
            else:
                fixed.append(np.zeros(x.shape, dtype=jax.dtypes.float0))
        aux_zero = tuple(
            np.zeros(a.shape, dtype=jax.dtypes.float0)
            if not jnp.issubdtype(a.dtype, jnp.floating)
            else jnp.zeros_like(a) for a in aux_in_)
        return tuple(fixed), aux_zero

    run.defvjp(run_fwd, run_bwd)
    results = run(tuple(data_in), tuple(aux_in))
    return tuple(results)


# ---------------------------------------------------------------------------
# Legacy NumpyOp / NDArrayOp (reference operator.py:126-372) — instances are
# callable on symbols; internally adapted onto the Custom machinery.
# ---------------------------------------------------------------------------

class _LegacyOpAdapter(CustomOp):
    """NDArrayOp-style dispatch: the instance's fwd/bwd take NDArrays."""

    def __init__(self, inst):
        self._inst = inst

    def forward(self, is_train, req, in_data, out_data, aux):
        self._inst.forward(in_data=in_data, out_data=out_data)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self._inst.backward(out_grad=out_grad, in_data=in_data,
                            out_data=out_data, in_grad=in_grad)


def _np_copy(arrays):
    # writable host copies (asnumpy may alias a read-only device buffer)
    return [np.array(a.asnumpy()) for a in arrays]


class _NumpyOpAdapter(_LegacyOpAdapter):
    """NumpyOp-style dispatch: the instance's fwd/bwd take numpy arrays."""

    def forward(self, is_train, req, in_data, out_data, aux):
        ins = _np_copy(in_data)
        outs = _np_copy(out_data)
        self._inst.forward(in_data=ins, out_data=outs)
        for dst, src in zip(out_data, outs):
            dst[:] = src

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        ograds = _np_copy(out_grad)
        ins = _np_copy(in_data)
        outs = _np_copy(out_data)
        igrads = _np_copy(in_grad)
        self._inst.backward(out_grad=ograds, in_data=ins, out_data=outs,
                            in_grad=igrads)
        for dst, src in zip(in_grad, igrads):
            dst[:] = src


class _LegacyProp(CustomOpProp):
    """Adapter exposing a PythonOp instance through CustomOpProp."""

    def __init__(self, instance):
        super().__init__(need_top_grad=instance.need_top_grad_)
        self._inst = instance

    def list_arguments(self):
        return self._inst.list_arguments()

    def list_outputs(self):
        return self._inst.list_outputs()

    def infer_shape(self, in_shape):
        return self._inst.infer_shape(in_shape)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        if isinstance(self._inst, NumpyOp):
            return _NumpyOpAdapter(self._inst)
        return _LegacyOpAdapter(self._inst)


class PythonOp(object):
    """Base of legacy python ops (reference operator.py:PythonOp)."""

    _legacy_count = [0]

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad
        self._op_type = None

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym_mod
        if self._op_type is None:    # one registry entry per instance
            self._op_type = "_legacy_python_op_%d" % PythonOp._legacy_count[0]
            PythonOp._legacy_count[0] += 1
            inst = self
            _PROP_REGISTRY[self._op_type] = lambda **kw: _LegacyProp(inst)
        kwargs["op_type"] = self._op_type
        return sym_mod.Custom(*args, **kwargs)

    def forward(self, in_data, out_data):
        raise NotImplementedError()

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError()

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def assign(self, dst, req, src):
        _assign(dst, req, src)


class NumpyOp(PythonOp):
    """Legacy numpy custom op (reference operator.py:NumpyOp): forward/
    backward receive numpy arrays."""

    def __init__(self, need_top_grad=True):
        super().__init__(need_top_grad)


class NDArrayOp(PythonOp):
    """Legacy NDArray custom op (reference operator.py:NDArrayOp)."""

    def __init__(self, need_top_grad=True):
        super().__init__(need_top_grad)
