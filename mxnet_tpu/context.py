"""Device context.

Mirrors the reference's ``Context`` (python/mxnet/context.py) with a TPU-first
mapping: ``mx.tpu(i)`` is the native device; ``mx.gpu(i)`` is accepted as an
alias for the i-th accelerator so reference scripts run unmodified
(BASELINE.json north star); ``mx.cpu(i)`` maps to the i-th XLA host-platform
device, which is how multi-device semantics are tested without hardware
(reference tests/python/unittest/test_model_parallel.py:30-31 uses cpu(0)/cpu(1)
the same way).
"""
from __future__ import annotations

import os
import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context(object):
    """A device context. devtype ids follow the reference
    (include/mxnet/base.h Context::kCPU=1, kGPU=2, kCPUPinned=3) with kTPU=4
    appended."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    # -- JAX mapping ------------------------------------------------------
    @property
    def jax_device(self):
        """The jax.Device this context denotes.

        Always a device addressable by THIS process: under the multi-process
        runtime (distributed.py) ``jax.devices()`` also lists peers' devices,
        but a worker's ``tpu(i)`` means its own i-th chip, exactly as a
        reference worker's ``gpu(i)`` is its local GPU i.
        """
        import jax
        if self.device_typeid in (1, 3):
            devs = (jax.local_devices(backend="cpu") if _has_platform("cpu")
                    else jax.local_devices())
        else:
            # gpu is an accelerator alias: use the default backend's devices
            # (TPU under axon; host-platform CPU devices in tests).
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                "%s: device_id %d out of range (%d %s devices visible)"
                % (self, self.device_id, len(devs), devs[0].platform if devs else "?"))
        return devs[self.device_id]

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx


def _has_platform(name):
    import jax
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def _default_device_type():
    """tpu if an accelerator backend is present, else cpu."""
    import jax
    plat = jax.default_backend()
    return "cpu" if plat == "cpu" else "tpu"


def cpu(device_id=0):
    """Return a CPU context (host-platform XLA device)."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Accelerator alias so reference scripts using mx.gpu() run on TPU."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the native device of this framework."""
    return Context("tpu", device_id)


def num_gpus():
    import jax
    return 0 if jax.default_backend() == "cpu" else len(jax.local_devices())


def num_tpus():
    return num_gpus()


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context(_default_device_type(), 0)
    return Context._default_ctx.value
