"""Image loading + augmentation pipeline.

API parity with the reference's python/mxnet/image.py (imdecode,
resize_short, fixed/random/center/random_size crops, the *Aug factories,
CreateAugmenter, ImageIter) plus ImageRecordIter — the reference's C++
RecordIO image iterator (reference src/io/iter_image_recordio_2.cc) rebuilt
on the host dependency engine.

TPU-native design note: the reference augments on NDArrays so the GPU can
help; on TPU, per-image augmentation is host work (tiny per-image XLA
dispatches would be latency-bound), so augmenters operate on numpy HWC
uint8/float32 arrays and whole batches transfer to device once per step.
Decode/augment fan out across engine workers (reference's multithreaded
ImageRecordIOParser2) while batch assembly serializes through a write var.
"""
from __future__ import annotations

import logging
import os
import random as pyrandom
import threading as _threading

import numpy as np

from . import io as mxio
from . import ndarray as nd
from . import recordio
from .base import (ENV_DATA_SERVERS, ENV_DATA_WORKERS, MXNetError,
                   get_env, register_env)

ENV_UPLOAD_THREADS = register_env(
    "MXNET_UPLOAD_THREADS", default=4,
    doc="Device-upload thread-pool size for batched host->device copies")
ENV_JPEG_DECODE_FAST = register_env(
    "MXNET_JPEG_DECODE_FAST", default=1,
    doc="0 switches the native training decode from the fast SIMD IDCT "
        "to exact byte-parity with cv2")
ENV_RECORDITER_NATIVE = register_env(
    "MXNET_RECORDITER_NATIVE", default=1,
    doc="0 disables the native libjpeg decode pipeline in ImageRecordIter")
ENV_RECORDITER_PROCS = register_env(
    "MXNET_RECORDITER_PROCS", default=1,
    doc="0 disables the process-parallel decode pipeline in "
        "ImageRecordIter")


# ---------------------------------------------------------------------------
# Augmentation RNG.  Augmenters draw from a THREAD-LOCAL rng when one has
# been installed (decode workers, the pipeline reader thread), falling back
# to the process-global modules otherwise (direct user calls keep reference
# semantics).  Pipelines reseed per CHUNK, keyed off a monotonically
# assigned chunk index — so a sample's augmentation is a pure function of
# (user seed, chunk index), independent of which worker the scheduler
# happens to hand the chunk to.
# ---------------------------------------------------------------------------


class _AugRngLocal(_threading.local):
    def __init__(self):
        self.py = None
        self.np = None


_AUG_RNG = _AugRngLocal()


def _rpy():
    return _AUG_RNG.py if _AUG_RNG.py is not None else pyrandom


def _rnp():
    return _AUG_RNG.np if _AUG_RNG.np is not None else np.random


def _seed_aug_rng(seed_val):
    _AUG_RNG.py = pyrandom.Random(int(seed_val))
    _AUG_RNG.np = np.random.RandomState(int(seed_val) % (2 ** 31))


# Deterministic per-(seed, chunk, epoch) augmentation seed and the
# default ImageNet normalization constants — ONE implementation shared
# with the out-of-process data service (its decode workers derive the
# identical seed for the identical global batch, which is what makes
# service output bit-identical to the in-process pipe).
from .data_service import common as _dsc  # noqa: E402
_chunk_seed = _dsc.chunk_seed

__all__ = [
    "imdecode", "imresize", "scale_down", "resize_short", "fixed_crop",
    "random_crop", "center_crop", "color_normalize", "random_size_crop",
    "ResizeAug", "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
    "RandomOrderAug", "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
    "HorizontalFlipAug", "CastAug", "PadAug", "CreateAugmenter", "ImageIter",
    "ImageRecordIter", "ImageRecordUInt8Iter",
]


def _cv2():
    import cv2
    return cv2


def imdecode(buf, flag=1, to_rgb=1, out=None):
    """Decode an image from bytes into an HWC uint8 array (reference
    image.py:imdecode; to_rgb=1 gives RGB, the reference's default).

    JPEG payloads decode through the native libjpeg path when available
    (shared with the mx.nd.imdecode op); everything else via cv2."""
    if isinstance(buf, nd.NDArray):
        buf = buf.asnumpy()
    from .ops.image_io import _decode_host
    img = _decode_host(bytes(buf), int(flag), int(to_rgb))
    return np.ascontiguousarray(img)


def imresize(src, w, h, interp=2):
    """Resize to exactly (w, h)."""
    cv2 = _cv2()
    out = cv2.resize(np.asarray(src), (int(w), int(h)), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def scale_down(src_size, size):
    """Scale down crop size if bigger than image size (reference
    image.py:scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize the shorter edge to `size` keeping aspect ratio."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop at a fixed location, optionally resizing to `size` (w, h)."""
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp=interp)
    return out


def random_crop(src, size, interp=2):
    """Random crop of `size` (upsamples if src smaller). Returns
    (img, (x0, y0, w, h))."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _rpy().randint(0, w - new_w)
    y0 = _rpy().randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop of `size`. Returns (img, (x0, y0, w, h))."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = int((w - new_w) / 2)
    y0 = int((h - new_h) / 2)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32)
    if mean is not None:
        src = src - np.asarray(mean, dtype=np.float32)
    if std is not None:
        src /= np.asarray(std, dtype=np.float32)
    return src


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area + aspect-ratio crop (inception-style)."""
    h, w = src.shape[:2]
    new_ratio = _rpy().uniform(*ratio)
    if new_ratio * h > w:
        max_area = w * int(w / new_ratio)
    else:
        max_area = h * int(h * new_ratio)
    min_area = min_area * h * w
    if max_area < min_area:
        return random_crop(src, size, interp)
    new_area = _rpy().uniform(min_area, max_area)
    new_w = int(np.sqrt(new_area * new_ratio))
    new_h = int(np.sqrt(new_area / new_ratio))
    new_w, new_h = min(new_w, w), min(new_h, h)
    x0 = _rpy().randint(0, w - new_w)
    y0 = _rpy().randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def ResizeAug(size, interp=2):
    def aug(src):
        return [resize_short(src, size, interp)]
    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]
    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]
    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]
    return aug


def RandomOrderAug(ts):
    def aug(src):
        src = [src]
        ts_ = list(ts)
        _rpy().shuffle(ts_)
        for t in ts_:
            src = [j for i in src for j in t(i)]
        return src
    return aug


def ColorJitterAug(brightness, contrast, saturation):
    """Random brightness/contrast/saturation jitter in random order."""
    ts = []
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if brightness > 0:
        def baug(src):
            alpha = 1.0 + _rpy().uniform(-brightness, brightness)
            return [src.astype(np.float32) * alpha]
        ts.append(baug)
    if contrast > 0:
        def caug(src):
            src = src.astype(np.float32)
            alpha = 1.0 + _rpy().uniform(-contrast, contrast)
            gray = (src * coef).sum(axis=2, keepdims=True)
            return [src * alpha + gray.mean() * (1.0 - alpha)]
        ts.append(caug)
    if saturation > 0:
        def saug(src):
            src = src.astype(np.float32)
            alpha = 1.0 + _rpy().uniform(-saturation, saturation)
            gray = (src * coef).sum(axis=2, keepdims=True)
            return [src * alpha + gray * (1.0 - alpha)]
        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    """PCA-based lighting noise (AlexNet style)."""
    def aug(src):
        alpha = _rnp().normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval)
        return [src.astype(np.float32) + rgb.astype(np.float32)]
    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]
    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if _rpy().random() < p:
            src = src[:, ::-1]
        return [src]
    return aug


def CastAug():
    def aug(src):
        return [src.astype(np.float32)]
    return aug


class PadAug(object):
    """Pad every border by ``pad`` pixels with ``fill_value`` before
    cropping — the reference C++ augmenter's ``pad`` param
    (image_aug_default.cc; the CIFAR recipe is pad=4 + rand_crop 32)."""

    def __init__(self, pad, fill_value=0):
        self.pad = int(pad)
        self.fill = fill_value

    def __call__(self, src, rs=None):
        import cv2
        p = self.pad
        out = cv2.copyMakeBorder(src, p, p, p, p, cv2.BORDER_CONSTANT,
                                 value=[self.fill] * 3)
        return [out]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2,
                    pad=0, fill_value=0):
    """Create the standard augmenter list (reference image.py:CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))

    if pad > 0:
        auglist.append(PadAug(pad, fill_value))

    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3,
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))

    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))

    auglist.append(CastAug())

    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))

    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))

    if mean is True:
        mean = np.array(_dsc.IMAGENET_MEAN)
    if std is True:
        std = np.array(_dsc.IMAGENET_STD)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(mxio.DataIter):
    """Image iterator with augmentation, reading .rec files or raw images
    listed in a .lst file (reference image.py:ImageIter).

    Supports path_imgrec (+ optional path_imgidx for shuffle/partition),
    or path_imglist + path_root, or an in-memory imglist.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", seed=None, **kwargs):
        super(ImageIter, self).__init__()
        # seeded shuffle order is reproducible regardless of which thread
        # calls reset(); seed=None keeps reference semantics (global rng)
        self._shuffle_rng = pyrandom.Random(seed) if seed is not None \
            else pyrandom
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        self.imgrec = None
        self.imgidx = None
        if path_imgrec:
            logging.info("loading recordio %s...", path_imgrec)
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")

        self.imglist = None
        if path_imglist:
            logging.info("loading image list %s...", path_imglist)
            imglist_d = {}
            imgkeys = []
            with open(path_imglist) as fin:
                for line in fin:
                    line = [i.strip() for i in line.strip().split("\t")]
                    label = np.array(line[1:-1], dtype=np.float32)
                    key = int(line[0])
                    imglist_d[key] = (label, line[-1])
                    imgkeys.append(key)
            self.imglist = imglist_d
            self.seq = imgkeys
        elif isinstance(imglist, list):
            imglist_d = {}
            imgkeys = []
            for i, img in enumerate(imglist):
                key = i
                label = np.array(img[0], dtype=np.float32) \
                    if not isinstance(img[0], (int, float)) \
                    else np.array([img[0]], dtype=np.float32)
                imglist_d[key] = (label, img[1])
                imgkeys.append(key)
            self.imglist = imglist_d
            self.seq = imgkeys
        elif self.imgidx is not None:
            self.seq = self.imgidx
        else:
            self.seq = None

        self.path_root = path_root
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise MXNetError(
                "data_shape must be (3, height, width), got %s"
                % (data_shape,))
        self.data_name = data_name
        self.label_name = label_name
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if self.seq is not None and num_parts > 1:
            chunk = len(self.seq) // num_parts
            self.seq = self.seq[part_index * chunk:(part_index + 1) * chunk]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [mxio.DataDesc(self.data_name,
                              (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [mxio.DataDesc(self.label_name,
                              (self.batch_size, self.label_width)
                              if self.label_width > 1
                              else (self.batch_size,))]

    def reset(self):
        if self.shuffle and self.seq is not None:
            self._shuffle_rng.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Returns (label, decoded image) for the next sample."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                if self.imglist is None:
                    return header.label, img
                return self.imglist[idx][0], img
            label, fname = self.imglist[idx]
            return label, self.read_image(fname)
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next_raw(self):
        """(label, raw jpeg bytes or decoded array) — split out so threaded
        iterators can separate serial IO from parallel decode."""
        return self.next_sample()

    def decode_augment(self, s):
        """Decode (if raw bytes) + augment one sample into HWC float32."""
        data = self.imdecode(s) if isinstance(s, bytes) else s
        self.check_valid_image(data)
        return self.augmentation_transform(data)

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), dtype=np.float32)
        batch_label = np.zeros((batch_size, self.label_width),
                               dtype=np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                try:
                    batch_data[i] = self.decode_augment(s)
                except (RuntimeError, MXNetError) as e:
                    logging.debug("Invalid image, skipping: %s", str(e))
                    continue
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        label = nd.array(batch_label[:, 0] if self.label_width == 1
                         else batch_label)
        return mxio.DataBatch([data], [label], pad=pad,
                              provide_data=self.provide_data,
                              provide_label=self.provide_label)

    __next__ = next

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError("data_shape should have length 3")
        if not data_shape[0] == 3:
            raise ValueError("This iterator expects the input (h, w, 3)")

    def check_valid_image(self, data):
        if len(data.shape) == 0:
            raise RuntimeError("Data shape is wrong")

    def imdecode(self, s):
        return imdecode(s)

    def read_image(self, fname):
        with open(os.path.join(self.path_root, fname), "rb") as fin:
            return imdecode(fin.read())

    def augmentation_transform(self, data):
        for aug in self.auglist:
            data = aug(data)[0]
        return data


# ---------------------------------------------------------------------------
# process-pool decode workers (the fast path).  cv2 in this environment does
# not release the GIL, so Python threads cannot scale decode+augment; worker
# PROCESSES are the faithful analog of the reference's C++ decode thread pool
# (iter_image_recordio_2.cc's omp parallel chunk decode).  Workers are
# spawned (not forked — forking after XLA init is unsafe) and only touch
# numpy/cv2.
# ---------------------------------------------------------------------------

_PP_AUG = None


def _pp_init(data_shape, aug_kwargs, seed):
    """Worker initializer.  Installs a thread-local aug rng seeded from the
    user seed; _pp_work_chunk reseeds it per CHUNK so augmentation is a pure
    function of (seed, chunk index) — independent of pid and of which
    worker the scheduler hands a chunk to."""
    global _PP_AUG
    _seed_aug_rng(_chunk_seed(seed, 0))
    _PP_AUG = CreateAugmenter(tuple(data_shape), **aug_kwargs)


def _pp_work(raw, augs=None):
    """bytes -> augmented CHW float32 (or None for an unusable image —
    decode OR augmentation failures skip the sample, like the reference
    parser's per-image error tolerance)."""
    augs = _PP_AUG if augs is None else augs
    try:
        d = imdecode(raw)
        for a in augs:
            d = a(d)[0]
        return np.ascontiguousarray(np.asarray(d, dtype=np.float32)
                                    .transpose(2, 0, 1))
    except Exception:  # noqa: BLE001
        return None


def _pp_work_chunk(raws, chunk_seed=None):
    """Decode+augment a chunk of records in one IPC round trip (amortizes
    submit/pickle overhead, like the reference's per-chunk omp decode)."""
    if chunk_seed is not None:
        _seed_aug_rng(chunk_seed)
    return [_pp_work(r) for r in raws]


class _AsyncPipeline(object):
    """Reader thread + bounded batch queue: the prefetching decorator shared
    by the decode pipelines (the reference's dmlc ThreadedIter prefetcher,
    iter_prefetcher.h).  Subclasses implement _one_epoch()."""

    def __init__(self, it, batch_size, prefetch, seed=0):
        import queue
        import threading

        self._it = it
        self._bs = batch_size
        self._seed = int(seed)
        self._epoch_no = 0   # epoch ordinal: chunk seeds derive from
        # (seed, epoch, chunk-within-epoch), so an abandoned (mid-epoch
        # reset) epoch can't make later epochs timing-dependent
        self._queue = queue.Queue(maxsize=max(1, prefetch))
        self._cmd = queue.Queue()
        self._empty_exc = queue.Empty  # bound now: __del__ may run during
        self._full_exc = queue.Full    # interpreter shutdown (no imports)
        self._at_end = False
        self._stopping = False
        self._abandon = False
        self._failed = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._cmd.put("epoch")
        _register_pipeline(self)

    def _run(self):
        while True:
            cmd = self._cmd.get()
            if cmd == "stop":
                break
            try:
                self._one_epoch()
                self._put(None)  # epoch end marker
            except BaseException as e:  # noqa: BLE001 — surface in next()
                if not self._stopping:
                    self._failed = e
                    self._put(("error", e))
                break

    def _put(self, item):
        """Bounded put that stays interruptible for shutdown."""
        while not self._stopping:
            try:
                self._queue.put(item, timeout=0.2)
                return
            except self._full_exc:
                continue

    def _one_epoch(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def _shutdown_extra(self):
        pass

    @staticmethod
    def _is_error(b):
        return isinstance(b, tuple) and len(b) == 2 and b[0] == "error"

    def next(self):
        if self._failed is not None:
            raise MXNetError("decode pipeline failed: %r" % (self._failed,))
        if self._at_end:
            raise StopIteration   # repeated next() after exhaustion
        b = self._queue.get()
        if b is None:
            self._at_end = True
            raise StopIteration
        if self._is_error(b):
            self._failed = b[1]
            self._at_end = True
            raise MXNetError("decode pipeline failed: %r" % (b[1],))
        return b

    def reset(self):
        if self._failed is not None:
            raise MXNetError(
                "decode pipeline failed earlier: %r" % (self._failed,))
        if not self._at_end:
            # abandon the in-flight epoch (reader checks the flag per
            # chunk) and drain to the end marker
            self._abandon = True
            while True:
                b = self._queue.get()
                if b is None:
                    break
                if self._is_error(b):
                    self._failed = b[1]
                    self._abandon = False
                    raise MXNetError(
                        "decode pipeline failed: %r" % (b[1],))
            self._abandon = False
        self._at_end = False
        self._it.reset()
        self._cmd.put("epoch")

    def shutdown(self):
        """Stop the reader thread BEFORE interpreter/XLA teardown — a
        daemon thread killed mid-XLA-call aborts the process.  No imports
        here: __del__ can run while the interpreter shuts down."""
        if not hasattr(self, "_queue"):
            # a subclass __init__ failed before _AsyncPipeline.__init__
            # ran (it cleans its own resources on that path); there is
            # no thread/queue to stop and __del__ must not raise
            return
        self._stopping = True
        try:
            self._cmd.put_nowait("stop")
        except Exception:  # noqa: BLE001
            pass
        try:
            while True:
                self._queue.get_nowait()   # unblock a full-queue put
        except self._empty_exc:
            pass
        except Exception:  # noqa: BLE001
            pass
        try:
            self._thread.join(timeout=5)
        except Exception:  # noqa: BLE001
            pass
        try:
            self._shutdown_extra()
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        self.shutdown()


class _ProcessPipeline(_AsyncPipeline):
    """Decode via spawned worker processes (cv2 in this environment does
    not release the GIL, so Python threads cannot scale decode+augment;
    worker PROCESSES are the faithful analog of the reference's C++ decode
    thread pool).  Single-core hosts decode inline on the reader thread."""

    def __init__(self, it, data_shape, batch_size, label_width, aug_kwargs,
                 num_workers, prefetch, dtype, allow_procs=True, seed=0):
        import concurrent.futures as cf
        import multiprocessing as mp

        self._shape = data_shape
        self._lw = label_width
        self._dtype = np.dtype(dtype) if dtype != "bfloat16" else dtype
        self._workers = max(1, min(num_workers, _host_cores()))
        if not allow_procs:
            self._workers = 1
        if self._workers > 1:
            # forkserver: workers fork from a clean server process — no XLA
            # state inherited (unlike fork) and no __main__ re-execution
            # (unlike spawn)
            try:
                ctx = mp.get_context("forkserver")
            except ValueError:
                ctx = mp.get_context("spawn")
            self._pool = cf.ProcessPoolExecutor(
                max_workers=self._workers, mp_context=ctx,
                initializer=_pp_init,
                initargs=(tuple(data_shape), dict(aug_kwargs), seed))
            self._augs = None
        else:
            self._pool = None
            self._augs = CreateAugmenter(tuple(data_shape), **aug_kwargs)
        super(_ProcessPipeline, self).__init__(it, batch_size, prefetch,
                                              seed=seed)

    def _shutdown_extra(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)

    def _one_epoch(self):
        from collections import deque
        chunk = max(1, min(16, self._bs))
        max_inflight = self._workers * 4
        self._epoch_no += 1
        chunk_in_epoch = 0
        inflight = deque()
        ready = []          # decoded (img, label) awaiting batch assembly
        exhausted = False
        while (not exhausted or inflight or ready) \
                and not self._stopping and not self._abandon:
            while not exhausted and len(inflight) < max_inflight:
                raws, labs = [], []
                for _ in range(chunk):
                    try:
                        lab, raw = self._it.next_raw()
                    except StopIteration:
                        exhausted = True
                        break
                    raws.append(raw)
                    labs.append(np.asarray(lab, dtype=np.float32))
                if raws:
                    cseed = _chunk_seed(self._seed, chunk_in_epoch,
                                        epoch=self._epoch_no)
                    chunk_in_epoch += 1
                    if self._pool is None:
                        # inline path: same per-chunk derivation, installed
                        # on the reader thread's thread-local rng (user
                        # threads' global RNG state is untouched)
                        _seed_aug_rng(cseed)
                        inflight.append((_Done([_pp_work(r, self._augs)
                                                for r in raws]), labs))
                    else:
                        inflight.append(
                            (self._pool.submit(_pp_work_chunk, raws, cseed),
                             labs))
            if inflight:
                fut, labs = inflight.popleft()
                for img, lab in zip(fut.result(), labs):
                    if img is not None:
                        ready.append((img, lab))
                while len(ready) >= self._bs:
                    self._emit(ready[:self._bs])
                    del ready[:self._bs]
            elif ready:
                self._emit(ready)
                ready = []

    def _emit(self, items):
        c, h, w = self._shape
        data = np.zeros((self._bs, c, h, w), np.float32)
        lab = np.zeros((self._bs, self._lw), np.float32)
        n = 0
        for d, l in items:
            data[n] = d
            lab[n] = l
            n += 1
        if n == 0:
            return
        if self._dtype == "bfloat16":
            import ml_dtypes
            data = data.astype(ml_dtypes.bfloat16)  # halve the H2D bytes
        elif np.dtype(self._dtype) == np.uint8:
            data = np.clip(data, 0, 255).astype(np.uint8)  # raw-pixel mode
        elif self._dtype != np.float32:
            data = data.astype(self._dtype)
        batch = mxio.DataBatch(
            [nd.array(data, dtype=data.dtype)],
            [nd.array(lab[:, 0] if self._lw == 1 else lab)],
            pad=self._bs - n)
        self._put(batch)


def _host_cores():
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:  # non-linux
        return os.cpu_count() or 1


def _rec_looks_jpeg(path_imgrec):
    """Peek at the first record's image payload: JPEG magic FFD8?"""
    try:
        r = recordio.MXRecordIO(path_imgrec, "r")
        try:
            s = r.read()
            if s is None:
                return True  # empty file: either path handles it
            _, img = recordio.unpack(s)
            head = bytes(img[:2])
            return head == b"\xff\xd8"
        finally:
            r.close()
    except Exception:  # noqa: BLE001 — be permissive, decode errors surface later
        return True


class _NativePipeline(_AsyncPipeline):
    """Decode via the native libjpeg pipeline (native/imagedec.cc) — the
    TPU-first rebuild of the reference's in-engine C++ decode threads
    (reference src/io/iter_image_recordio_2.cc:27-80).  The whole
    decode+augment+normalize+pack stage runs in C++ with the GIL released;
    batches land in preallocated buffers and device-transfer from the
    reader thread, overlapping the consumer's step dispatch."""

    #: aug knobs the native path implements; anything else falls back to
    #: the python/process pipeline.
    SUPPORTED = frozenset(("resize", "rand_crop", "rand_mirror",
                           "mean", "std"))

    #: device-upload threads: each nd.array() call may BLOCK for a full
    #: host->device round trip (tunneled/remote devices have ~100 ms
    #: transfer latency at fine batch sizes even when bandwidth is ample),
    #: so uploads run on a small pool with order-preserving delivery.
    #: MXNET_UPLOAD_THREADS overrides (1 = serial uploads on the pool).
    UPLOAD_THREADS = int(get_env(ENV_UPLOAD_THREADS, "4"))

    def __init__(self, it, data_shape, batch_size, label_width, aug_kwargs,
                 num_workers, prefetch, dtype, layout="NCHW", seed=0,
                 device_transform=None, host_batches=False):
        import concurrent.futures as _cf
        import ctypes

        from . import native as _native
        # host_batches: deliver decode output as numpy-backed DataBatches
        # with no device transfer — the exact product the reference's C++
        # parser hands out (mshadow CPU tensors).  Callers that feed a
        # non-JAX consumer (torch bridge, custom eval loops) or measure
        # pure decode+augment throughput use this.
        self._host_batches = bool(host_batches)
        self._uploader = _cf.ThreadPoolExecutor(
            max_workers=self.UPLOAD_THREADS,
            thread_name_prefix="mxtpu-upload")
        # optional device-side per-batch map (e.g. a jitted
        # normalize/transpose/cast): runs on the uploader threads so its
        # dispatch latency overlaps across in-flight batches
        self._device_transform = device_transform
        self._pipe = None
        try:
            self._init_native(it, data_shape, batch_size, label_width,
                              aug_kwargs, num_workers, prefetch, dtype,
                              layout, seed)
        except BaseException:
            # release the pool/pipe before re-raising so a fallback path
            # (cv2/process pipeline) doesn't inherit leaked threads
            self._uploader.shutdown(wait=False)
            if self._pipe:
                _native.get_lib().MXTPUImgPipeDestroy(self._pipe)
                self._pipe = None
            raise

    def _init_native(self, it, data_shape, batch_size, label_width,
                     aug_kwargs, num_workers, prefetch, dtype, layout, seed):
        import ctypes

        from . import native as _native
        lib = _native.get_lib()
        if lib is None or not getattr(lib, "_has_imagedec", False):
            raise MXNetError("native image pipeline unavailable")
        unsupported = set(aug_kwargs) - self.SUPPORTED
        if unsupported:
            raise MXNetError("native image pipeline does not implement %s"
                             % sorted(unsupported))
        self._lib = lib
        self._ct = ctypes
        c, h, w = data_shape
        if c != 3:
            raise MXNetError("native image pipeline expects 3-channel data")
        self._shape = tuple(data_shape)
        self._lw = label_width
        self._layout = layout
        if dtype == "bfloat16":
            import ml_dtypes
            self._np_dtype = np.dtype(ml_dtypes.bfloat16)
            code = 2
        elif np.dtype(dtype) == np.uint8:
            self._np_dtype = np.dtype(np.uint8)
            code = 0
        elif np.dtype(dtype) == np.float32:
            self._np_dtype = np.dtype(np.float32)
            code = 1
        else:
            raise MXNetError("native image pipeline: unsupported dtype %r"
                             % (dtype,))
        self._dtype = dtype
        mean = aug_kwargs.get("mean")
        std = aug_kwargs.get("std")
        if mean is True:
            mean = np.array(_dsc.IMAGENET_MEAN)
        if std is True:
            std = np.array(_dsc.IMAGENET_STD)
        # honor the requested thread count (reference preprocess_threads
        # semantics) — C++ decode threads are cheap to park, and tests
        # exercise the pool even on small hosts
        nthreads = max(1, int(num_workers))
        # training profile defaults to the fast SIMD IDCT (~1.5x decode
        # throughput, within +-2 of the exact output — augmentation noise
        # dwarfs it); MXNET_JPEG_DECODE_FAST=0 restores byte parity with
        # cv2 (the mx.nd.imdecode op is always exact)
        fast_dct = get_env(ENV_JPEG_DECODE_FAST, "1") != "0"
        # one shared constructor with the data-service worker's decoder
        # (data_service.common) — the two paths must configure the C++
        # pipe identically or the bit-identity contract breaks
        self._pipe, self._pipe_keepalive = _dsc.open_native_pipe(
            lib, h, w, aug_kwargs.get("resize"),
            aug_kwargs.get("rand_crop"), aug_kwargs.get("rand_mirror"),
            code, 0 if layout == "NCHW" else 1, mean, std, fast_dct,
            nthreads)
        if not self._pipe:
            raise MXNetError("native image pipeline: create failed")
        super(_NativePipeline, self).__init__(it, batch_size, prefetch,
                                              seed=seed)

    def _shutdown_extra(self):
        try:
            self._uploader.shutdown(wait=False, cancel_futures=True)
        except Exception:  # noqa: BLE001
            pass
        # only free the C++ pipe once the reader thread is provably out of
        # MXTPUImgPipeDecodeBatch — if the join timed out, leak the pipe
        # rather than delete an object a live thread is executing in
        if self._pipe and not self._thread.is_alive():
            self._lib.MXTPUImgPipeDestroy(self._pipe)
            self._pipe = None

    def _upload(self, out, lab_arr, pad):
        """Host batch -> device DataBatch (runs on an uploader thread; the
        nd.array device transfer may block for a full link round trip)."""
        if self._host_batches:
            return mxio.DataBatch(
                [out], [lab_arr[:, 0] if self._lw == 1 else lab_arr],
                pad=pad)
        data = nd.array(out, dtype=out.dtype)
        if self._device_transform is not None:
            data = nd.NDArray._from_jax(self._device_transform(data._data))
        labels = nd.array(lab_arr[:, 0] if self._lw == 1 else lab_arr)
        return mxio.DataBatch([data], [labels], pad=pad)

    def _one_epoch(self):
        from collections import deque
        ct = self._ct
        bs = self._bs
        c, h, w = self._shape
        bshape = (bs, c, h, w) if self._layout == "NCHW" else (bs, h, w, c)
        self._epoch_no += 1
        chunk_in_epoch = 0
        it = self._it
        u8p = ct.POINTER(ct.c_uint8)
        valid = np.empty(bs, np.uint8)
        exhausted = False
        inflight = deque()   # ordered upload futures

        def drain(block):
            while inflight and (block or inflight[0].done()):
                self._put(inflight.popleft().result())

        while not exhausted and not self._stopping and not self._abandon:
            raws, labs = [], []
            for _ in range(bs):
                try:
                    lab, raw = it.next_raw()
                except StopIteration:
                    exhausted = True
                    break
                raws.append(raw)
                labs.append(lab)
            n = len(raws)
            if n == 0:
                break
            cseed = _chunk_seed(self._seed, chunk_in_epoch,
                                epoch=self._epoch_no)
            chunk_in_epoch += 1
            # fresh buffer per batch: the device transfer is async wrt this
            # loop, so a shared buffer could be rewritten mid-copy
            out = np.empty(bshape, self._np_dtype) if n == bs \
                else np.zeros(bshape, self._np_dtype)
            bufs = (ct.c_void_p * n)(
                *[ct.cast(ct.c_char_p(r), ct.c_void_p) for r in raws])
            lens = (ct.c_uint64 * n)(*[len(r) for r in raws])
            valid[:] = 0
            nv = self._lib.MXTPUImgPipeDecodeBatch(
                self._pipe, bufs, lens, n, out.ctypes.data_as(ct.c_void_p),
                valid.ctypes.data_as(u8p), cseed)
            if nv == 0:
                # an entire batch of undecodable records is a dataset-level
                # problem (e.g. non-JPEG payloads), not per-image noise —
                # fail loudly instead of silently draining the epoch
                raise MXNetError(
                    "native image pipeline: every record in a batch failed "
                    "to decode — is this a non-JPEG .rec? Set "
                    "MXNET_RECORDITER_NATIVE=0 to use the cv2 pipeline")
            keep = np.flatnonzero(valid[:n])
            lab_arr = np.zeros((bs, self._lw), np.float32)
            lab_arr[:nv] = np.asarray(labs, np.float32).reshape(
                n, -1)[keep][:, :self._lw]
            if nv < n:   # compact valid samples to the front, zero the pad
                out[:nv] = out[keep]
                out[nv:] = 0
            inflight.append(
                self._uploader.submit(self._upload, out, lab_arr, bs - nv))
            drain(block=False)
            while len(inflight) > self.UPLOAD_THREADS + 2:  # backpressure
                self._put(inflight.popleft().result())
        drain(block=True)


_live_pipelines = None


def _register_pipeline(p):
    global _live_pipelines
    if _live_pipelines is None:
        import atexit
        import weakref
        _live_pipelines = weakref.WeakSet()

        def _stop_all():
            for pl in list(_live_pipelines):
                pl.shutdown()
        atexit.register(_stop_all)
    _live_pipelines.add(p)


class _Done(object):
    """Immediately-resolved future (inline decode path)."""

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


def _translate_cxx_aug_params(kwargs):
    """Map the reference C++ iterator's parameter names
    (src/io/image_aug_default.cc: mean_r/g/b, max_random_scale, ...) onto
    CreateAugmenter's kwargs, so reference training scripts run unmodified.
    Unsupported knobs are dropped with a log line rather than an error,
    matching the spirit of the reference's "best effort" augmentation
    defaults; exact-parity consumers should pass aug_list explicitly."""
    kw = dict(kwargs)
    out = {}
    mean = [kw.pop("mean_r", 0.0), kw.pop("mean_g", 0.0),
            kw.pop("mean_b", 0.0)]
    if any(mean):
        out["mean"] = np.asarray(mean, dtype=np.float32)
    std = [kw.pop("std_r", 0.0), kw.pop("std_g", 0.0), kw.pop("std_b", 0.0)]
    if any(std):
        out["std"] = np.asarray(std, dtype=np.float32)
    if "rand_crop" in kw:
        out["rand_crop"] = bool(kw.pop("rand_crop"))
    if "rand_mirror" in kw:
        out["rand_mirror"] = bool(kw.pop("rand_mirror"))
    if "resize" in kw:
        out["resize"] = kw.pop("resize")
    # random scale: the C++ pipeline rescales the source image before the
    # crop; the closest Python-side analog is the random-sized crop
    mx_scale = kw.pop("max_random_scale", 1.0)
    mn_scale = kw.pop("min_random_scale", 1.0)
    if (mx_scale != 1.0 or mn_scale != 1.0) and out.get("rand_crop"):
        out["rand_resize"] = True
    if "pad" in kw:
        out["pad"] = kw.pop("pad")
        # the reference C++ augmenter pads with 255 unless told otherwise
        # (image_aug_default.cc:109 fill_value default) — scripts passing
        # pad= alone must get white padding, not black
        out["fill_value"] = kw.pop("fill_value", 255)
    dropped = {}
    for name in ("max_rotate_angle", "max_random_rotate_angle",
                 "max_aspect_ratio", "max_random_aspect_ratio",
                 "max_shear_ratio", "max_random_shear_ratio",
                 "max_random_h", "max_random_s", "max_random_l",
                 "inter_method", "max_img_size",
                 "min_img_size", "mirror", "rand_gray", "scale", "max_crop_size",
                 "min_crop_size", "random_h", "random_s", "random_l",
                 "rotate", "verbose"):
        if name in kw:
            dropped[name] = kw.pop(name)
    if dropped:
        logging.info("ImageRecordIter: ignoring augmentation params with no "
                     "Python-pipeline analog yet: %s", sorted(dropped))
    out.update(kw)  # anything else goes through (and typos will raise)
    return out


class ImageRecordIter(mxio.DataIter):
    """Threaded RecordIO image iterator — the reference's C++
    ImageRecordIOParser2 pipeline (reference src/io/iter_image_recordio_2.cc:
    parser -> augmenter -> batch loader -> prefetcher) rebuilt on the host
    dependency engine: per-image decode+augment ops fan out across engine
    workers, batch assembly serializes on a write var, and `prefetch_buffer`
    assembled batches stay in flight ahead of the consumer.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1, shuffle=False,
                 shuffle_chunk_seed=0, seed=None, part_index=0, num_parts=1,
                 prefetch_buffer=4, preprocess_threads=4, round_batch=True,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NCHW", device_transform=None, host_batches=False,
                 data_service=None, device_augment=None, **aug_kwargs):
        super(ImageRecordIter, self).__init__(batch_size)
        from . import random as _random
        self._eff_seed = _random.get_seed() if seed is None else int(seed)
        aug_kwargs = _translate_cxx_aug_params(aug_kwargs)
        has_custom_augs = "aug_list" in aug_kwargs
        self._layout = layout
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("layout must be NCHW or NHWC")
        # Incompatible-flag checks depend only on constructor args and must
        # precede any resource acquisition (ImageIter's record/index file
        # handles, and below it _NativePipeline's reader thread, uploader
        # pool and C++ pipe), so the error path leaks nothing.
        if host_batches and device_transform is not None:
            raise MXNetError(
                "host_batches yields raw numpy batches — a device_transform "
                "would be silently skipped; pass one or the other")
        # Multi-process data service (docs/how_to/performance.md "Scaling
        # the input pipeline"): data_service=True uses preprocess_threads
        # worker PROCESSES; MXTPU_DATA_WORKERS=N turns it on (and sizes
        # the fleet) without touching call sites.
        # data_service='host:port,host:port' (or MXTPU_DATA_SERVERS)
        # streams from the network tier's server fleet instead of this
        # host's cores.  data_service=False forces the in-process
        # pipelines even when either env is set.
        self._service = None
        self._service_iter = None
        self._dev_aug = None
        self._it = None
        if device_augment is False:
            device_augment = None   # explicit off == unset; 0 is a
            # REAL margin (center crop + mirror/normalize on device)
        env_workers = int(get_env(ENV_DATA_WORKERS, 0) or 0)
        env_servers = str(get_env(ENV_DATA_SERVERS, "") or "").strip()
        # data_service forms: None (env decides), False/0/"" (opt out),
        # True or any other truthy (local service), 'host:p,host:p' or
        # a list/tuple of addresses (network tier)
        explicit_servers = None
        explicit_local = False
        if isinstance(data_service, str):
            explicit_servers = data_service.strip() or None
        elif isinstance(data_service, (list, tuple)):
            explicit_servers = list(data_service) or None
        elif data_service is not None:
            explicit_local = bool(data_service)
        servers = explicit_servers
        if servers is None and data_service is None and env_servers:
            servers = env_servers
        env_routed = data_service is None
        use_local = explicit_local or (
            env_routed and not servers and env_workers > 0)
        if servers or use_local:
            # an EXPLICIT data_service=True sizes the fleet from the
            # call's preprocess_threads; the env sizes only env-routed
            # iterators (it must not silently override a call site —
            # the bench's scaling sweep depends on this).  On the
            # network tier preprocess_threads is the per-SERVER decode
            # worker count.
            workers = env_workers if (use_local and env_routed) \
                else max(1, int(preprocess_threads))
            try:
                self._init_service(
                    path_imgrec, path_imgidx, data_shape, batch_size,
                    label_width, shuffle, part_index, num_parts, workers,
                    dtype, layout, aug_kwargs, has_custom_augs,
                    device_transform, host_batches, data_name, label_name,
                    servers=servers, device_augment=device_augment)
            except MXNetError:
                if not env_routed:   # explicitly requested: surface it
                    raise
                if device_augment is not None:
                    # an explicit device-augment ask must not silently
                    # degrade to host augmentation on a routing fallback
                    raise
                logging.warning(
                    "ImageRecordIter: MXTPU_DATA_WORKERS/MXTPU_DATA_"
                    "SERVERS is set but this configuration cannot route "
                    "through the data service; using the in-process "
                    "pipeline", exc_info=True)
        elif device_augment is not None:
            raise MXNetError(
                "device_augment rides the data-service transports: pass "
                "data_service=True / a server list, or set "
                "MXTPU_DATA_WORKERS / MXTPU_DATA_SERVERS")
        if self._service is not None:
            self.batch_size = batch_size
            self.data_shape = tuple(data_shape)
            self.label_width = label_width
            self._dtype = dtype
            self._host_batches = bool(host_batches)
            self._device_transform = device_transform
            self._data_name = data_name
            self._label_name = label_name
            return
        self._it = ImageIter(
            batch_size, data_shape, label_width=label_width,
            path_imgrec=path_imgrec, path_imgidx=path_imgidx,
            shuffle=shuffle, part_index=part_index, num_parts=num_parts,
            data_name=data_name, label_name=label_name,
            seed=self._eff_seed, **aug_kwargs)
        self._pipeline = None
        # Fastest path: native C++ decode pipeline (libjpeg, GIL-released),
        # when the requested augmentations are natively implemented AND the
        # first record looks like JPEG (PNG/BMP .rec files take the cv2
        # paths — libjpeg cannot decode them).
        if (not has_custom_augs
                and get_env(ENV_RECORDITER_NATIVE, "1") != "0"
                and set(aug_kwargs) <= _NativePipeline.SUPPORTED
                and _rec_looks_jpeg(path_imgrec)):
            try:
                self._pipeline = _NativePipeline(
                    self._it, tuple(data_shape), batch_size, label_width,
                    aug_kwargs, preprocess_threads, prefetch_buffer, dtype,
                    layout=layout, seed=self._eff_seed,
                    device_transform=device_transform,
                    host_batches=host_batches)
            except (MXNetError, ImportError, OSError):
                # ImportError: ml_dtypes missing for dtype='bfloat16';
                # OSError: ctypes load failure — the cv2/process path may
                # still work on such hosts, so fall through
                self._pipeline = None
        if device_transform is not None and self._pipeline is None:
            raise MXNetError(
                "device_transform needs the native image pipeline")
        if host_batches and not isinstance(self._pipeline, _NativePipeline):
            raise MXNetError(
                "host_batches needs the native image pipeline (libjpeg)")
        if self._pipeline is None and layout != "NCHW":
            raise MXNetError(
                "layout='NHWC' needs the native image pipeline (libjpeg); "
                "it is unavailable or the augmentations aren't native")
        # Next: spawned decode-worker processes (cv2 holds the GIL, so
        # in-process threading cannot scale; see _ProcessPipeline).  Custom
        # aug_list closures aren't picklable -> engine-threaded fallback,
        # also selectable via MXNET_CPU_WORKER_NTHREADS-style env.
        import sys as _sys
        main_file = getattr(_sys.modules.get("__main__"), "__file__", None)
        # worker processes re-import __main__ (standard multiprocessing
        # contract: scripts guard with if __name__ == '__main__'); from a
        # REPL/stdin only the inline reader-thread mode is available
        spawnable_main = main_file is not None and os.path.exists(main_file)
        use_pipeline = (not has_custom_augs
                        and get_env(ENV_RECORDITER_PROCS, "1") != "0")
        if self._pipeline is None and use_pipeline:
            self._pipeline = _ProcessPipeline(
                self._it, tuple(data_shape), batch_size, label_width,
                aug_kwargs, preprocess_threads, prefetch_buffer, dtype,
                allow_procs=spawnable_main, seed=self._eff_seed)
        if self._pipeline is None:
            from . import engine as eng
            self._engine = eng.Engine(num_workers=max(2, preprocess_threads))
            self._img_base = 0   # global sample ordinal: engine-path
            # augmentation seeds derive per image from (seed, ordinal)
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._dtype = dtype
        self._prefetch = max(1, prefetch_buffer)
        self._queue = []
        self._drained = False
        if self._pipeline is None:
            # Serializes raw record reads (the source is sequential).
            self._read_var = self._engine.new_variable()
            self._start_prefetch()

    def _init_service(self, path_imgrec, path_imgidx, data_shape,
                      batch_size, label_width, shuffle, part_index,
                      num_parts, workers, dtype, layout, aug_kwargs,
                      has_custom_augs, device_transform, host_batches,
                      data_name, label_name, servers=None,
                      device_augment=None):
        """Route through the data service — local
        (``data_service.DataService``, this host's cores) or the
        network tier (``data_service.NetDataService``, a
        ``tools/data_server.py`` fleet); raises MXNetError for
        configurations neither can express."""
        from .data_service import (DataService, DataServiceIter,
                                   NetDataService)
        if path_imgidx is None:
            raise MXNetError(
                "data_service needs path_imgidx (sharded readers plan "
                "from the index)")
        if has_custom_augs:
            raise MXNetError(
                "data_service cannot ship a custom aug_list to worker "
                "processes")
        unsupported = set(aug_kwargs) - _NativePipeline.SUPPORTED
        if unsupported:
            raise MXNetError(
                "data_service does not implement augmentations %s"
                % sorted(unsupported))
        if not servers and not _rec_looks_jpeg(path_imgrec):
            # worker processes decode through their own native libjpeg
            # pipes — a PNG/BMP .rec would crash-loop every worker at
            # runtime; fail eligibility here so env routing falls back
            # to the cv2 pipelines instead.  (Network tier: the paths
            # belong to the SERVER hosts — this host may hold no copy;
            # the server's handshake reply surfaces dataset problems.)
            raise MXNetError(
                "data_service needs a JPEG-payload .rec (the worker "
                "decode pipes are libjpeg); this file's first record "
                "is not JPEG")
        svc_shape = tuple(data_shape)
        svc_aug = dict(aug_kwargs)
        svc_dtype = dtype
        if device_augment is not None:
            # in-graph augmentation (kernels/augment.py, the `augment`
            # seam of MXTPU_FUSED_KERNELS): the transport ships a
            # RAW-DECODED uint8 canvas with a crop margin and the
            # device does crop/mirror/normalize as traced ops, seeded
            # per global batch.  Seam off = the EXACT host-augmented
            # path below, by construction.
            from .kernels import fused_enabled
            if host_batches:
                raise MXNetError(
                    "device_augment produces device arrays — it cannot "
                    "combine with host_batches")
            if fused_enabled("augment"):
                from .kernels.augment import DeviceAugment
                margin = 16 if device_augment is True \
                    else int(device_augment)
                self._dev_aug = DeviceAugment(
                    svc_shape, margin=margin,
                    rand_crop=bool(aug_kwargs.get("rand_crop")),
                    rand_mirror=bool(aug_kwargs.get("rand_mirror")),
                    mean=aug_kwargs.get("mean"),
                    std=aug_kwargs.get("std"), layout=layout,
                    dtype=dtype)
                svc_shape = self._dev_aug.canvas_shape
                svc_aug = {k: v for k, v in aug_kwargs.items()
                           if k == "resize"}
                svc_dtype = "uint8"   # raw bytes on the wire: 4x less
            else:
                logging.info(
                    "ImageRecordIter: MXTPU_FUSED_KERNELS disables the "
                    "augment kernel — using the exact host-augmented "
                    "path")
        fast_dct = get_env(ENV_JPEG_DECODE_FAST, "1") != "0"
        if servers:
            self._service = NetDataService(
                servers, path_imgrec, path_imgidx, svc_shape,
                batch_size, label_width=label_width, shuffle=shuffle,
                seed=self._eff_seed, part_index=part_index,
                num_parts=num_parts, workers_per_server=workers,
                dtype=svc_dtype, layout=layout, aug=svc_aug,
                fast_dct=fast_dct)
        else:
            self._service = DataService(
                path_imgrec, path_imgidx, svc_shape, batch_size,
                label_width=label_width, shuffle=shuffle,
                seed=self._eff_seed, part_index=part_index,
                num_parts=num_parts, num_workers=workers,
                dtype=svc_dtype, layout=layout, aug=svc_aug,
                fast_dct=fast_dct)
        # copy=False: the host_batches contract (views valid until the
        # next pull) matches the bench's ephemeral reads, and the device
        # path makes its own guaranteed copy in _next_service
        self._service_iter = DataServiceIter(
            self._service, data_name=data_name, label_name=label_name,
            copy=False)

    @property
    def provide_data(self):
        dt = np.dtype("float32" if self._dtype == "bfloat16"
                      else self._dtype)
        if self._service is not None:
            if self._dev_aug is not None:
                # the transport carries the uint8 canvas; consumers see
                # the post-augmentation (device-side) product
                shape = (self.batch_size,) + self._dev_aug.per_layout(
                    self._dev_aug.out_shape)
                return [mxio.DataDesc(self._data_name, shape, dtype=dt)]
            descs = self._service_iter.provide_data
            return [mxio.DataDesc(d.name, d.shape, dtype=dt) for d in descs]
        descs = []
        for d in self._it.provide_data:
            shape = d.shape
            if self._layout == "NHWC":
                n, c, h, w = shape
                shape = (n, h, w, c)
            descs.append(mxio.DataDesc(d.name, shape, dtype=dt))
        return descs

    @property
    def provide_label(self):
        if self._service is not None:
            return self._service_iter.provide_label
        return self._it.provide_label

    def _produce_one(self):
        """Pipeline one batch: a serial read op pulls batch_size raw records,
        then per-image decode+augment ops fan out across engine workers, and
        an assemble op (depending on all decode vars) builds the DataBatch."""
        import threading

        it = self._it
        c, h, w = self.data_shape
        slot = {}
        done = threading.Event()
        raw = {}

        def read_raw():
            samples = []
            try:
                for _ in range(self.batch_size):
                    samples.append(it.next_raw())
            except StopIteration:
                pass
            raw["samples"] = samples

        decoded = np.zeros((self.batch_size, h, w, c), dtype=np.float32)
        valid = [False] * self.batch_size
        img_base = self._img_base
        self._img_base += self.batch_size

        def decode_i(i):
            samples = raw["samples"]
            if i >= len(samples):
                return
            try:
                # per-image deterministic stream: independent of which
                # engine worker thread runs this op
                _seed_aug_rng(_chunk_seed(self._eff_seed, img_base + i))
                decoded[i] = it.decode_augment(samples[i][1])
                valid[i] = True
            except (RuntimeError, MXNetError) as e:
                logging.debug("Invalid image, skipping: %s", str(e))

        def assemble():
            samples = raw["samples"]
            if not samples:
                slot["eof"] = True
                done.set()
                return
            keep = [i for i in range(len(samples)) if valid[i]]
            n = len(keep)
            data = np.zeros_like(decoded)
            label = np.zeros((self.batch_size, self.label_width), "f")
            for j, i in enumerate(keep):
                data[j] = decoded[i]
                lab = samples[i][0]
                label[j] = lab
            out = data.transpose(0, 3, 1, 2)
            if np.dtype(self._dtype) == np.uint8:
                out = np.clip(out, 0, 255)  # clamp, don't wrap
            batch = mxio.DataBatch(
                [nd.array(out).astype(self._dtype)],
                [nd.array(label[:, 0] if self.label_width == 1 else label)],
                pad=self.batch_size - n,
                provide_data=self.provide_data,
                provide_label=self.provide_label)
            slot["batch"] = batch
            done.set()

        read_done = self._engine.new_variable()
        self._engine.push(read_raw, mutable_vars=(self._read_var, read_done),
                          name="imagerec_read")
        dec_vars = []
        for i in range(self.batch_size):
            dv = self._engine.new_variable()
            self._engine.push(lambda i=i: decode_i(i),
                              const_vars=(read_done,), mutable_vars=(dv,),
                              name="imagerec_decode")
            dec_vars.append(dv)
        self._engine.push(assemble, const_vars=tuple(dec_vars),
                          name="imagerec_assemble")
        # Dependency-ordered deletion: vars reclaim after their consumers.
        self._engine.delete_variable(read_done)
        for dv in dec_vars:
            self._engine.delete_variable(dv)
        self._queue.append((slot, done))

    def _start_prefetch(self):
        while len(self._queue) < self._prefetch and not self._drained:
            self._produce_one()

    def reset(self):
        if self._service is not None:
            self._service_iter.reset()
            return
        if self._pipeline is not None:
            self._pipeline.reset()
            return
        self._engine.wait_for_all()
        self._queue = []
        self._drained = False
        self._it.reset()
        self._start_prefetch()

    def _next_service(self):
        """One batch off the service collector.  host_batches hands the
        zero-copy views through (valid until the next pull — the exact
        product the C++ parser handed out); the device path uploads with
        ``copy=True`` (on the CPU backend a plain device_put ALIASES the
        numpy buffer — releasing the ring slot would corrupt the "device"
        array) and releases the slot immediately.  With device_augment
        the uploaded canvas runs through the in-graph augmentation op,
        seeded by the batch's chunk seed (bit-reproducible across
        worker/server counts by construction)."""
        batch = self._service_iter.next()
        if self._host_batches:
            return batch
        import jax.numpy as jnp
        uploaded = jnp.array(batch.data[0], copy=True)
        if self._dev_aug is not None:
            uploaded = self._dev_aug(uploaded, batch.aug_seed,
                                     self.batch_size - batch.pad)
        data = nd.NDArray._from_jax(uploaded)
        if self._device_transform is not None:
            data = nd.NDArray._from_jax(self._device_transform(data._data))
        labels = nd.array(batch.label[0])
        batch.release()   # device copies made: recycle the ring slot
        return mxio.DataBatch([data], [labels], pad=batch.pad,
                              provide_data=self.provide_data,
                              provide_label=self.provide_label)

    def next(self):
        if self._service is not None:
            batch = self._next_service()
            batch.provide_data = self.provide_data
            batch.provide_label = self.provide_label
            return batch
        if self._pipeline is not None:
            batch = self._pipeline.next()
            batch.provide_data = self.provide_data
            batch.provide_label = self.provide_label
            return batch
        if not self._queue:
            raise StopIteration
        slot, done = self._queue.pop(0)
        done.wait()
        if "eof" in slot:
            self._drained = True
            self._queue = []
            raise StopIteration
        self._start_prefetch()
        return slot["batch"]

    __next__ = next

    def stats(self):
        """Per-stage data-service counters (ring occupancy, stall times,
        respawns); None for the in-process pipelines."""
        if self._service is not None:
            return self._service.stats()
        return None

    def close(self):
        if self._service is not None:
            self._service_iter.close()
            return
        if self._pipeline is not None:
            self._pipeline.shutdown()
            return
        self._engine.wait_for_all()
        self._engine.shutdown()


def ImageRecordUInt8Iter(path_imgrec, data_shape, batch_size, **kwargs):
    """Raw uint8 record iterator (reference iter_image_recordio_2.cc:579
    ImageRecordUInt8Iter): decode+augment without normalization, batches
    emitted as uint8 — callers cast/normalize on device (the TPU-friendly
    layout: 4x fewer H2D bytes than f32)."""
    for bad in ("mean_r", "mean_g", "mean_b", "std_r", "std_g", "std_b"):
        if kwargs.get(bad):
            raise MXNetError(
                "ImageRecordUInt8Iter emits raw uint8; normalization "
                "params like %r belong on-device (or use ImageRecordIter)"
                % bad)
    return ImageRecordIter(path_imgrec, data_shape, batch_size,
                           dtype="uint8", **kwargs)
