"""Testing utilities (reference python/mxnet/test_utils.py, 905 LoC).

Provides the reference's three numeric oracles:
- ``check_numeric_gradient``: finite differences vs symbolic backward
- ``check_symbolic_forward`` / ``check_symbolic_backward``: vs numpy refs
- ``check_consistency``: same graph on two device types (cpu vs tpu)
"""
from __future__ import annotations

import numpy as np

from .base import get_env, register_env
from .context import Context, cpu, current_context
from .ndarray import NDArray, array as nd_array

ENV_TEST_DEVICE = register_env(
    "MXNET_TEST_DEVICE", scope="test",
    doc="Overrides test_utils.default_context() (e.g. cpu:0)")

__all__ = [
    "default_context", "assert_almost_equal", "rand_ndarray", "rand_shape_nd",
    "check_numeric_gradient", "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "simple_forward",
]


def default_context():
    """Context under test — switchable via MXNET_TEST_DEVICE (reference
    test_utils.py default_context via env)."""
    dev = get_env(ENV_TEST_DEVICE)
    if dev:
        name, _, idx = dev.partition(":")
        return Context(name, int(idx or 0))
    return current_context()


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    if isinstance(a, NDArray):
        a = a.asnumpy()
    if isinstance(b, NDArray):
        b = b.asnumpy()
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s" % names)


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, ctx=None, dtype=np.float32):
    return nd_array(np.random.uniform(-1, 1, size=shape).astype(dtype), ctx=ctx)


def _as_numpy_dict(location, arg_names):
    if isinstance(location, dict):
        return {k: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v, dtype=np.float32))
                for k, v in location.items()}
    return {name: (v.asnumpy() if isinstance(v, NDArray) else np.asarray(v, dtype=np.float32))
            for name, v in zip(arg_names, location)}


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    ex = sym.bind(ctx, {k: nd_array(v, ctx=ctx) for k, v in inputs.items()})
    outputs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outputs[0] if len(outputs) == 1 else outputs


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    ctx = ctx or default_context()
    loc = _as_numpy_dict(location, sym.list_arguments())
    args = {k: nd_array(v, ctx=ctx) for k, v in loc.items()}
    aux = {k: nd_array(v, ctx=ctx) for k, v in (aux_states or {}).items()} or None
    ex = sym.bind(ctx, args, aux_states=aux)
    outputs = ex.forward()
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-5, aux_states=None, grad_req="write",
                            ctx=None):
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    loc = _as_numpy_dict(location, arg_names)
    args = {k: nd_array(v, ctx=ctx) for k, v in loc.items()}
    grads = {k: nd_array(np.zeros_like(v), ctx=ctx) for k, v in loc.items()}
    aux = {k: nd_array(v, ctx=ctx) for k, v in (aux_states or {}).items()} or None
    ex = sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                  aux_states=aux)
    ex.forward(is_train=True)
    ex.backward([nd_array(g, ctx=ctx) for g in out_grads])
    expected = expected if isinstance(expected, dict) else \
        dict(zip(arg_names, expected))
    for name, exp in expected.items():
        assert_almost_equal(grads[name].asnumpy(), exp, rtol=rtol, atol=atol,
                            names=("grad(%s)" % name, "expected"))
    return {k: v.asnumpy() for k, v in grads.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=1e-4, grad_nodes=None, ctx=None):
    """Finite-difference gradient check (reference test_utils.py
    check_numeric_gradient): perturb each input element, compare the numeric
    d(sum(outputs*proj))/dx against the symbolic backward."""
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    loc = _as_numpy_dict(location, arg_names)
    grad_nodes = grad_nodes or [n for n in arg_names if n in loc]

    # random fixed projection so a vector output reduces to a scalar
    _, out_shapes, _ = sym.infer_shape(**{k: v.shape for k, v in loc.items()})
    proj = [np.random.normal(0, 1.0, size=s).astype(np.float32)
            for s in out_shapes]

    args = {k: nd_array(v, ctx=ctx) for k, v in loc.items()}
    grads = {k: nd_array(np.zeros_like(v), ctx=ctx) for k, v in loc.items()}
    aux = {k: nd_array(v, ctx=ctx) for k, v in (aux_states or {}).items()} or None
    ex = sym.bind(ctx, args, args_grad=grads, grad_req="write", aux_states=aux)
    ex.forward(is_train=True)
    ex.backward([nd_array(p, ctx=ctx) for p in proj])
    sym_grads = {k: grads[k].asnumpy().copy() for k in grad_nodes}

    def fwd_scalar():
        # is_train=True so the finite-difference probes the same function the
        # symbolic backward differentiated (BatchNorm batch-stats path etc.)
        outs = ex.forward(is_train=True)
        return sum(float((o.asnumpy() * p).sum()) for o, p in zip(outs, proj))

    for name in grad_nodes:
        base = loc[name].copy()
        numeric = np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            args[name][:] = base
            fp = fwd_scalar()
            flat[i] = orig - numeric_eps
            args[name][:] = base
            fm = fwd_scalar()
            flat[i] = orig
            args[name][:] = base
            num_flat[i] = (fp - fm) / (2 * numeric_eps)
        np.testing.assert_allclose(
            sym_grads[name], numeric, rtol=rtol, atol=atol,
            err_msg="numeric vs symbolic gradient mismatch for %s" % name)


def check_consistency(sym, ctx_list, scale=1.0, rtol=1e-3, atol=1e-4):
    """Run the same symbol on several contexts and compare outputs
    (reference test_utils.py check_consistency, used by
    tests/python/gpu/test_operator_gpu.py for cpu-vs-gpu)."""
    if not ctx_list:
        return
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    shapes = ctx_list[0]["shapes"] if isinstance(ctx_list[0], dict) else None
    outputs = []
    arg_vals = None
    aux_vals = None
    for spec in ctx_list:
        ctx = spec["ctx"]
        shapes = spec.get("shapes", shapes)
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        if arg_vals is None:
            arg_vals = {n: (np.random.normal(0, scale, size=s).astype(np.float32))
                        for n, s in zip(arg_names, arg_shapes)}
            # aux convention: running means 0, running variances 1
            aux_vals = {n: (np.ones(s, np.float32) if "var" in n
                            else np.zeros(s, np.float32))
                        for n, s in zip(aux_names, aux_shapes)}
        args = {k: nd_array(v, ctx=ctx) for k, v in arg_vals.items()}
        aux = {k: nd_array(v, ctx=ctx) for k, v in aux_vals.items()} or None
        ex = sym.bind(ctx, args, aux_states=aux)
        outputs.append([o.asnumpy() for o in ex.forward()])
    for other in outputs[1:]:
        for a, b in zip(outputs[0], other):
            np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)
    return outputs
