"""Runtime kernel escape hatch — the TPU-native analog of MXRtc.

The reference lets users hand-write CUDA at runtime and push it through
NVRTC (include/mxnet/mxrtc.h:16-89, python/mxnet/rtc.py: ``MXRtc(name,
inputs, outputs, kernel_src).push(...)``).  On TPU the corresponding
escape hatch is a **Pallas kernel**: a Python function lowered to a
Mosaic/TPU kernel by ``jax.experimental.pallas``.  This module makes such
kernels first-class framework ops:

- :func:`register_kernel` — register any JAX/Pallas callable as an op; it
  immediately becomes available as ``mx.nd.<name>`` and ``mx.sym.<name>``
  and participates in executor fusion, autograd (via jax.vjp, or a custom
  ``vjp``), and the Module stack.
- :func:`elementwise_pallas_kernel` — wrap a Pallas kernel *body*
  (``kernel(in_ref, out_ref)``) into a callable with sane VMEM block specs,
  falling back to interpreter mode off-TPU so kernels are testable on the
  virtual CPU mesh.
- :class:`MXRtc` — the reference's class shape (name/inputs/outputs +
  ``push``); the kernel is a Python/Pallas function instead of a CUDA
  source string (documented divergence: there is no NVRTC on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import OP_REGISTRY, register

__all__ = ["register_kernel", "elementwise_pallas_kernel", "MXRtc",
           "on_tpu"]


def _inject(reg_name):
    """Make a freshly registered op callable as mx.nd/<name> and
    mx.sym.<name> (the autogen modules are populated at import; late
    registrations self-inject)."""
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    opdef = OP_REGISTRY[reg_name]
    if reg_name not in vars(sym_mod):
        vars(sym_mod)[reg_name] = sym_mod._make_symbol_function(opdef,
                                                                reg_name)
    if reg_name not in vars(nd_mod):
        vars(nd_mod)[reg_name] = nd_mod._make_ndarray_function(opdef,
                                                               reg_name)


def register_kernel(name, fn=None, *, input_names=("data",), num_outputs=1,
                    infer_shape=None, needs_rng=False, vjp=None, **opdef_kw):
    """Register a JAX/Pallas callable as a framework op.

    Usable as a decorator::

        @mx.rtc.register_kernel("my_scale")
        def my_scale(data, scalar=2.0):
            return my_pallas_scale(data, scalar)

        y = mx.nd.my_scale(x, scalar=3.0)
        s = mx.sym.my_scale(mx.sym.Variable("data"), scalar=3.0)

    ``vjp``: optional ``vjp(primals..., cotangents...) -> grads``.
    Plain-JAX kernels differentiate automatically; **pallas_call kernels
    need an explicit vjp** (Pallas has no reverse-mode transpose — pair
    the forward kernel with a backward kernel, pallas_guide.md "Patterns:
    Custom VJP"), otherwise the op is forward-only.
    """
    def _do(f):
        import inspect

        if name in OP_REGISTRY:
            raise MXNetError("kernel/op %r already registered" % name)
        wrapped = f
        if vjp is not None:
            def wrapped(*arrays, **attrs):
                # jax.custom_vjp can't bind kwargs, so close over the
                # (static) attrs per call; traced values all ride in
                # ``arrays``.  Under jit this traces once per attr-set.
                @jax.custom_vjp
                def _core(*arr):
                    return f(*arr, **attrs)

                def _fwd(*arr):
                    return f(*arr, **attrs), arr

                def _bwd(res, g):
                    gs = g if isinstance(g, (tuple, list)) else (g,)
                    grads = vjp(*res, *gs, **attrs)
                    if not isinstance(grads, (tuple, list)):
                        grads = (grads,)
                    return tuple(grads)

                _core.defvjp(_fwd, _bwd)
                return _core(*arrays)

            wrapped.__doc__ = f.__doc__
            # keep f's declared parameter surface for attr validation and
            # the executor's framework-attr filtering
            wrapped.__signature__ = inspect.signature(f)
        register(name, input_names=input_names, num_outputs=num_outputs,
                 infer_shape=infer_shape, needs_rng=needs_rng,
                 **opdef_kw)(wrapped)
        _inject(name)
        return f
    if fn is not None:
        return _do(fn)
    return _do


def on_tpu():
    """Whether a real TPU backend is available — the tier selector for
    two-tier kernels (mxnet_tpu/kernels/): compiled Pallas on TPU, the
    fused-lax reference (or ``interpret=True``) elsewhere."""
    try:
        return jax.default_backend() == "tpu" or any(
            d.platform == "tpu" for d in jax.devices())
    except Exception:  # noqa: BLE001
        return False


_on_tpu = on_tpu  # historical private alias


def elementwise_pallas_kernel(kernel_body, interpret=None):
    """Wrap an elementwise Pallas kernel body ``kernel(in_ref, out_ref)``
    into ``fn(x) -> y`` with whole-array VMEM blocks.

    ``interpret=None`` auto-selects: compiled on TPU backends, interpreter
    elsewhere (so the same kernel runs on the virtual CPU mesh in tests —
    the MXRtc story never had that).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = not _on_tpu()

    def fn(x):
        return pl.pallas_call(
            kernel_body,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x)
    return fn


class MXRtc(object):
    """Reference-API-shaped runtime kernel (python/mxnet/rtc.py MXRtc).

    The reference compiles ``kernel`` as CUDA source via NVRTC; here
    ``kernel`` is a Python function over jax arrays (typically a
    pallas_call wrapper).  ``push`` mirrors the reference call shape; the
    grid/block dims are accepted for signature parity and passed through
    to kernels that want them.
    """

    def __init__(self, name, inputs, outputs, kernel):
        if isinstance(kernel, str):
            raise MXNetError(
                "MXRtc on TPU takes a Python/Pallas kernel function, not "
                "CUDA source (no NVRTC on TPU; see mxnet_tpu/rtc.py)")
        self.name = name
        self.input_names = [n for n, _ in inputs]
        self.output_names = [n for n, _ in outputs]
        self.kernel = kernel

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        """Run the kernel: reads ``inputs`` NDArrays, writes ``outputs``."""
        from .ndarray import NDArray
        from .ops.registry import fn_signature_info
        arrays = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
                  for x in inputs]
        names, has_var_kw = fn_signature_info(self.kernel)
        if has_var_kw or {"grid_dims", "block_dims"} & set(names):
            res = self.kernel(*arrays, grid_dims=grid_dims,
                              block_dims=block_dims)
        else:
            res = self.kernel(*arrays)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        if len(res) != len(outputs):
            raise MXNetError("kernel %s returned %d outputs, expected %d"
                             % (self.name, len(res), len(outputs)))
        for out, r in zip(outputs, res):
            out._data = r.astype(out._data.dtype)
