"""Torch interoperability (the reference's plugin/torch + python torch.py).

The reference embeds Torch7 modules/criterions into MXNet graphs
(plugin/torch/torch_module-inl.h: module parameters become MXNet args,
forward/backward call into TH) and exposes TH math as `mx.th.*`
(python/mxnet/torch.py).  This rebuild wraps modern PyTorch (CPU) through
the CustomOp protocol:

- ``TorchModule``: a ``torch.nn.Module`` as a symbol-producing layer whose
  torch parameters are MXNet arguments (initialized/updated/checkpointed
  by MXNet optimizers; gradients via torch autograd on the host).
- ``TorchCriterion``: a torch loss as an output layer (backward injects
  the torch gradient, ignoring head grads — loss-layer convention).
- ``mx.th``: TH-style math functions executed by torch on host arrays.

TPU note: torch runs on the host CPU, so graphs containing these layers
execute eagerly around them (same engine-callback behavior as the
reference plugin, which runs TH on the engine's CPU/GPU queue).  Use them
for interop/porting, not hot paths.
"""
from __future__ import annotations

import numpy as np  # noqa: F401 — host copies for torch interop

from . import operator as _op
from . import symbol as _sym
from .base import MXNetError

__all__ = ["TorchModule", "TorchCriterion", "th"]

_MODULE_REGISTRY = {}


def _torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover - torch is in the image
        raise MXNetError("the torch bridge needs pytorch installed") from e


class _TorchModuleOp(_op.CustomOp):
    def __init__(self, tmod, param_names):
        self._tmod = tmod
        self._param_names = param_names

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _torch()
        params = dict(self._tmod.named_parameters())
        with torch.no_grad():
            for name, arr in zip(self._param_names, in_data[1:]):
                params[name].copy_(torch.from_numpy(
                    np.array(arr.asnumpy())))
        x = torch.from_numpy(np.array(in_data[0].asnumpy()))
        if is_train:
            self._x = x.requires_grad_(True)
            self._y = self._tmod(self._x)
            out = self._y.detach().numpy()
        else:
            with torch.no_grad():
                out = self._tmod(x).numpy()
        self.assign(out_data[0], req[0], out)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _torch()
        params = [dict(self._tmod.named_parameters())[n]
                  for n in self._param_names]
        head = torch.from_numpy(np.array(out_grad[0].asnumpy()))
        grads = torch.autograd.grad(
            self._y, [self._x] + params, grad_outputs=head,
            allow_unused=True)
        for i, g in enumerate(grads):
            gnp = np.zeros(in_data[i].shape, np.float32) if g is None \
                else g.detach().numpy()
            self.assign(in_grad[i], req[i], gnp)


class _TorchModuleProp(_op.CustomOpProp):
    def __init__(self, torch_key=None, **_):
        super().__init__(need_top_grad=True)
        self._tmod, self._out_shape_fn = _MODULE_REGISTRY[str(torch_key)]
        self._param_names = [n for n, _ in self._tmod.named_parameters()]

    def list_arguments(self):
        return ["data"] + ["torch_%s" % n.replace(".", "_")
                           for n in self._param_names]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        params = dict(self._tmod.named_parameters())
        p_shapes = [tuple(params[n].shape) for n in self._param_names]
        out = self._out_shape_fn(tuple(in_shape[0]))
        return [tuple(in_shape[0])] + p_shapes, [out], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _TorchModuleOp(self._tmod, self._param_names)


_op.register("_TorchModule")(_TorchModuleProp)


def _infer_out_shape(tmod, in_shape):
    torch = _torch()
    with torch.no_grad():
        y = tmod(torch.zeros(*in_shape))
    return tuple(y.shape)


def TorchModule(torch_module, data, name="torch"):
    """Wrap a ``torch.nn.Module`` as a symbol layer.

    The module's parameters appear as MXNet arguments named
    ``<name>_torch_<param>`` — initialized, updated, and checkpointed by
    MXNet like any other weight (reference plugin/torch/torch_module).

    Example::

        net = mx.torch_bridge.TorchModule(torch.nn.Linear(10, 4), data,
                                          name="tl")
    """
    key = "%s@%d" % (name, id(torch_module))
    _MODULE_REGISTRY[key] = (
        torch_module, lambda s: _infer_out_shape(torch_module, s))
    return _sym.Custom(data, op_type="_TorchModule", torch_key=key,
                       name=name)


class _TorchCriterionOp(_op.CustomOp):
    def __init__(self, crit):
        self._crit = crit

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _torch()
        self._x = torch.from_numpy(
            np.array(in_data[0].asnumpy())).requires_grad_(True)
        self._t = torch.from_numpy(np.array(in_data[1].asnumpy()))
        loss = self._crit(self._x, self._t)
        self._loss = loss
        self.assign(out_data[0], req[0],
                    np.asarray(loss.detach().numpy()).reshape(1))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _torch()
        (g,) = torch.autograd.grad(self._loss, [self._x])
        # loss layer: inject the criterion gradient, ignore head grads
        self.assign(in_grad[0], req[0], g.detach().numpy())
        self.assign(in_grad[1], req[1],
                    np.zeros(in_data[1].shape, np.float32))


class _TorchCriterionProp(_op.CustomOpProp):
    def __init__(self, torch_key=None, **_):
        super().__init__(need_top_grad=False)
        self._crit = _MODULE_REGISTRY[str(torch_key)][0]

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["loss"]

    def infer_shape(self, in_shape):
        # loss emitted as shape (1,) like the reference criterion
        return [tuple(in_shape[0]), tuple(in_shape[1])], [(1,)], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _TorchCriterionOp(self._crit)


_op.register("_TorchCriterion")(_TorchCriterionProp)


def TorchCriterion(criterion, data, label, name="torchloss"):
    """Wrap a torch loss (e.g. ``torch.nn.MSELoss()``) as an output layer
    (reference plugin/torch/torch_criterion)."""
    key = "%s@%d" % (name, id(criterion))
    _MODULE_REGISTRY[key] = (criterion, None)
    return _sym.Custom(data, label, op_type="_TorchCriterion",
                       torch_key=key, name=name)


class _ThNamespace(object):
    """`mx.th.*` — TH-style math executed by torch on the host (reference
    python/mxnet/torch.py exposes the TH function registry the same way).
    Accepts/returns NDArray."""

    def __getattr__(self, fname):
        torch = _torch()
        fn = getattr(torch, fname, None)
        if fn is None:
            raise AttributeError("torch has no function %r" % fname)

        def call(*args, **kwargs):
            from . import ndarray as nd
            targs = [torch.from_numpy(np.array(a.asnumpy()))
                     if isinstance(a, nd.NDArray) else a for a in args]
            out = fn(*targs, **kwargs)
            if isinstance(out, torch.Tensor):
                return nd.array(out.numpy(), dtype=out.numpy().dtype)
            return out
        call.__name__ = fname
        return call


th = _ThNamespace()
