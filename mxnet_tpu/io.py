"""Data iterators.

Re-design of the reference's two-tier IO stack: the Python ``DataIter``
protocol (python/mxnet/io.py, 743 LoC) and the C++ chained-decorator
pipeline (src/io/, ~4,700 LoC: parser → batch loader → prefetcher).
The TPU version keeps the protocol and the iterator zoo; heavy decode
paths live behind the same interfaces (RecordIO in recordio.py, image
augmentation in image.py).
"""
from __future__ import annotations

import logging
import os
import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array as nd_array

__all__ = [
    "DataDesc", "DataBatch", "StagedBatch", "DataIter", "NDArrayIter",
    "ResizeIter", "PrefetchingIter", "MNISTIter", "CSVIter",
]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Data description with layout (reference io.py DataDesc; layouts like
    NCHW/TNC drive the batch-slice axis in data-parallel training)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            return [DataDesc(n, s, t) for (n, s), (_, t) in zip(shapes, types)]
        return [DataDesc(n, s) for n, s in shapes]


class DataBatch(object):
    """One mini-batch (reference io.py DataBatch)."""

    def __init__(self, data, label=None, pad=0, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def release(self):
        """Hand transport-owned buffers back to the producer.  A no-op
        for ordinary batches; slot-backed batches (the shared-memory
        data service) override it PER INSTANCE, and consumers that are
        done with the arrays — or have copied them, like
        ``DevicePrefetchIter``'s snapshot — call it to recycle the slot
        early.  Must be idempotent."""


class StagedBatch(DataBatch):
    """A DataBatch whose inputs are ALREADY placed on the mesh.

    ``staged`` maps input name -> device array, sharded/cast exactly the
    way ``SPMDTrainer._shard_batch`` would place it (see
    ``SPMDTrainer.stage_batch``); a trainer handed a StagedBatch skips the
    per-step host->device transfer entirely, which is how
    ``dataflow.DevicePrefetchIter`` overlaps the upload of batch N+1 with
    the execution of batch N.  The host-side ``data``/``label`` references
    are kept (no extra copy — they are the source iterator's arrays) so
    host consumers (metrics in blocking mode, the executor-group path,
    fault-injection re-staging) still see a plain DataBatch.
    """

    def __init__(self, staged, data=None, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        super().__init__(data, label=label, pad=pad, index=index,
                         provide_data=provide_data,
                         provide_label=provide_label)
        self.staged = dict(staged)


class DataIter(object):
    """Iterator protocol: reset/next/iter + provide_data/provide_label."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        # dynamic dispatch, NOT `__next__ = next`: subclasses override
        # next() (the reference's own custom-iterator recipe) and the
        # for-loop protocol must reach the override
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0

    def close(self):
        """Release background resources (threads, worker processes,
        shared memory).  A no-op for plain in-memory iterators; iterators
        owning a pipeline (``ImageRecordIter``, ``DataServiceIter``,
        ``DevicePrefetchIter``) override it, so generic consumers can
        always call ``it.close()`` when done."""


def _init_data(data, allow_empty, default_name):
    """Normalize data into a list of (name, numpy array) — reference
    io.py _init_data."""
    if data is None:
        if not allow_empty:
            raise ValueError("data must not be None")
        return []
    if isinstance(data, (NDArray, np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("empty data list")
        data = {(default_name if len(data) == 1 else "_%d_%s" % (i, default_name)): d
                for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("invalid data type %s" % type(data))
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py NDArrayIter):
    shuffle, last_batch_handle in {'pad', 'discard', 'roll_over'}."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            idx = np.random.permutation(self.num_data)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n
        if self.num_data < batch_size:
            raise MXNetError("batch_size %d > data size %d"
                             % (batch_size, self.num_data))
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, source):
        if self.cursor + self.batch_size <= self.num_data:
            return [nd_array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in source]
        # pad with wrapped-around samples
        pad = self.batch_size - (self.num_data - self.cursor)
        return [nd_array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch, optionally resetting
    the inner iterator on exhaustion (reference io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    __next__ = next

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread double-buffering over one or more iterators —
    the Python analog of the reference's dmlc ThreadedIter prefetcher
    (src/io/iter_prefetcher.h:50-53)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.n_iter = len(iters)
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self._errors = [None] * self.n_iter

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                # slot i is lock-free by design: data_taken[i]/
                # data_ready[i] form a strict handshake — exactly one
                # side owns the slot at any moment, and Event.set/wait
                # provide the happens-before edge a lock would
                try:
                    self.next_batch[i] = self._next_with_retry(i)  # mxlint: disable=repo-shared-mutation
                except StopIteration:
                    self.next_batch[i] = None  # mxlint: disable=repo-shared-mutation
                except Exception as e:  # noqa: BLE001 — surfaced to consumer
                    # retries exhausted (or a real bug): hand the error to
                    # the consuming thread instead of dying silently and
                    # hanging it on data_ready forever
                    self._errors[i] = e  # mxlint: disable=repo-shared-mutation
                    self.next_batch[i] = None  # mxlint: disable=repo-shared-mutation
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=(self, i), daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    def _next_with_retry(self, i):
        """Pull the next batch through the shared retry discipline
        (resilience.retrying_next: MXTPU_DATA_RETRIES with backoff;
        StopIteration and real bugs pass straight through — see its
        docstring for the no-cursor-advance contract)."""
        from .resilience import retrying_next
        return retrying_next(self.iters[i], name="prefetch[%d].next" % i)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                     for d in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        self._errors = [None] * self.n_iter
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        for i, err in enumerate(self._errors):
            if err is not None:
                # safe without a lock: data_ready[i] is set (waited on
                # above) and data_taken[i] clear, so the prefetch thread
                # is parked — the consumer owns the slot here
                self._errors[i] = None  # mxlint: disable=repo-shared-mutation
                # release ONLY the failed iterator's thread to refetch;
                # healthy iterators keep their in-flight batches.  Pairing
                # survives when the failed source did not advance past the
                # batch (the transient-IO case); a source that consumed the
                # record before failing cannot be realigned here — with
                # multiple iters, reset() after an exhausted-retry error is
                # the only guaranteed realignment
                self.data_ready[i].clear()
                self.data_taken[i].set()
                raise err
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([(b.label or []) for b in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    __next__ = next

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


def _read_idx_images(path):
    with open(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("bad MNIST image file %s" % path)
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)


def _read_idx_labels(path):
    with open(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("bad MNIST label file %s" % path)
        return np.frombuffer(f.read(), dtype=np.uint8)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (reference src/io/iter_mnist.cc).  Reads the
    standard ubyte files; ``flat`` selects (N,784) vs (N,1,28,28)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=0, data_name="data",
                 label_name="softmax_label", **kwargs):
        imgs = _read_idx_images(image).astype(np.float32) / 255.0
        lbls = _read_idx_labels(label).astype(np.float32)
        imgs = imgs.reshape(len(imgs), -1) if flat else \
            imgs.reshape(len(imgs), 1, imgs.shape[1], imgs.shape[2])
        if shuffle:
            # seeded shuffle (the reference iterator honors `seed`,
            # src/io/iter_mnist.cc)
            perm = np.random.RandomState(seed).permutation(len(imgs))
            imgs, lbls = imgs[perm], lbls[perm]
        if not silent:
            logging.info("MNISTIter: load %d images, shuffle=%s", len(imgs),
                         bool(shuffle))
        super().__init__(imgs, lbls, batch_size=batch_size, shuffle=False,
                         data_name=data_name, label_name=label_name)


class CSVIter(NDArrayIter):
    """CSV iterator (reference src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label[:, 0]
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard",
                         **kwargs)
