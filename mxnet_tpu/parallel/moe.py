"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

New capability beyond the reference (SURVEY §2.3: "Expert parallelism:
NO").  GShard-style top-2 routed FFN: a gating matmul scores tokens, each
token is dispatched to its top experts within a per-expert capacity, the
expert FFNs run as one batched (E, C, d) einsum whose E axis is sharded
over 'ep' — GSPMD turns the dispatch/combine einsums into all_to_all over
ICI — and combine weights re-mix the expert outputs.

Pattern references: GShard (Lepikhin et al. 2020), Switch Transformer —
see PAPERS.md.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["moe_init", "moe_ffn", "moe_shardings"]


def moe_init(rng, d_model, d_hidden, num_experts, dtype=jnp.float32):
    """Parameters: gate (d, E), w1 (E, d, h), b1 (E, h), w2 (E, h, d),
    b2 (E, d)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (num_experts, d_model, d_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((num_experts, d_hidden), dtype),
        "w2": jax.random.normal(k3, (num_experts, d_hidden, d_model),
                                dtype) * s2,
        "b2": jnp.zeros((num_experts, d_model), dtype),
    }


def moe_shardings(axis="ep"):
    """PartitionSpecs for moe_init params: experts sharded over ``axis``."""
    from jax.sharding import PartitionSpec as P
    return {"gate": P(), "w1": P(axis, None, None), "b1": P(axis, None),
            "w2": P(axis, None, None), "b2": P(axis, None)}


def _top2_dispatch(logits, capacity):
    """Token -> (expert, capacity slot) routing tensors.

    logits: (T, E).  Returns dispatch (T, E, C) in {0,1} and combine
    (T, E, C) with the renormalized top-2 gate weights; tokens overflowing
    an expert's capacity are dropped (their combine weight is 0), the
    GShard contract.
    """
    T, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    g1 = jnp.max(gates, axis=-1)
    e1 = jnp.argmax(gates, axis=-1)
    gates2 = gates * (1.0 - jax.nn.one_hot(e1, E, dtype=gates.dtype))
    g2 = jnp.max(gates2, axis=-1)
    e2 = jnp.argmax(gates2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def route(e, prior_counts):
        onehot = jax.nn.one_hot(e, E, dtype=jnp.float32)      # (T, E)
        pos = jnp.cumsum(onehot, axis=0) - onehot + prior_counts
        keep = (pos < capacity) & (onehot > 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32)              # (T, E, C)
        disp = slot * keep[..., None]
        return disp, prior_counts + jnp.sum(onehot * keep, axis=0)

    disp1, counts = route(e1, jnp.zeros((E,), jnp.float32))
    disp2, _ = route(e2, counts)
    dispatch = disp1 + disp2
    combine = disp1 * g1[:, None, None] + disp2 * g2[:, None, None]
    return dispatch, combine


def moe_ffn(params, x, capacity_factor=2.0, activation=jax.nn.relu):
    """Top-2 MoE FFN.  x: (B, S, d) -> (B, S, d).

    Shard params with :func:`moe_shardings` (and the batch over 'dp') and
    jit over the mesh: GSPMD turns the tec,td->ecd dispatch einsum into
    the all_to_all that carries tokens to their experts' devices.
    """
    B, S, d = x.shape
    T = B * S
    E = params["w1"].shape[0]
    capacity = int(np.ceil(capacity_factor * T * 2 / E))
    tokens = x.reshape(T, d)
    logits = tokens @ params["gate"]
    dispatch, combine = _top2_dispatch(logits, capacity)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, params["w1"])
                   + params["b1"][:, None, :])
    # bias on empty slots is harmless: combine is zero there
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
        + params["b2"][:, None, :]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.reshape(B, S, d)
