"""SPMDTrainer — one fused, mesh-sharded training step.

This is the TPU-native execution path that replaces the reference's whole
per-batch machinery (executor fan-out per device + KVStore push/pull +
optimizer on server, SURVEY §3.1/§3.4): forward, backward, gradient
AllReduce and the optimizer update are ONE jit-compiled XLA program,
annotated with shardings over a named Mesh.  GSPMD partitions it and
inserts the collectives (psum of grads over 'dp', AllGather for 'tp'
weights, ...) — lowered onto ICI, with buffer donation so parameters
update in-place in HBM.

Numerics match the reference's dist_sync protocol: grads are summed over
the dp axis and rescaled by 1/global_batch, then the optimizer rule (the
same sgd_update/adam_update ops the reference's server runs) applies once.
"""
from __future__ import annotations

import math
import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import optimizer as opt_mod
from ..base import MXNetError, register_env
from ..executor import _build_eval
from ..ndarray import NDArray
from ..io import DataDesc

__all__ = ["SPMDTrainer", "SUPPORTED_OPTIMIZERS",
           "DEFAULT_GUARD_FLUSH_INTERVAL"]

# optimizers with an in-graph update rule (_apply_update); Module's fused
# path consults this before engaging
SUPPORTED_OPTIMIZERS = ("sgd", "ccsgd", "adam", "rmsprop")

ENV_GRAD_SYNC = register_env(
    "MXNET_GRAD_SYNC", default="allreduce",
    doc="Gradient sync for the fused dp step: allreduce (replicated "
        "params), zero (ZeRO weight-sharded data parallelism, one "
        "gather block at step start) or zero3 (fully sharded: "
        "layer-grouped on-demand gathers, backward re-gather, "
        "reduce-scatter gradients)")

#: guard-counter flush cadence when deferred metrics are installed with no
#: explicit MXTPU_METRIC_INTERVAL (interval 0 = fold metrics on reads
#: only): the guard still syncs every this-many steps so skip logging and
#: the divergence abort lag by a bounded, documented amount instead of a
#: whole epoch
DEFAULT_GUARD_FLUSH_INTERVAL = 25


def _slice_shape(idx, shape):
    """Shape of shape[idx] for a tuple of slices (no allocation)."""
    out = []
    for sl, n in zip(idx, shape):
        start, stop, step = sl.indices(n)
        out.append(max(0, -(-(stop - start) // step)))
    return tuple(out)


def _spec_for(name, shape, rules):
    """Resolve a parameter's PartitionSpec from regex rules; default
    replicated."""
    for pattern, spec in (rules or {}).items():
        if re.match(pattern, name):
            spec = P(*spec) if not isinstance(spec, P) else spec
            if len(spec) > len(shape):
                raise MXNetError(
                    "sharding spec %s has more axes than param %s%s"
                    % (spec, name, shape))
            return spec
    return P()


class SPMDTrainer(object):
    """Fused sharded training step for a Symbol + Optimizer."""

    #: argnums of ``step(params, aux, opt_state, extras, ...)`` donated
    #: to XLA so the whole carry updates in place in HBM.  A class
    #: attribute so the static analyzer's fixture trainers can seed a
    #: donation violation (tests/test_analysis.py) — production code
    #: must not override it.
    DONATE_ARGNUMS = (0, 1, 2, 3)

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="dp", param_shardings=None,
                 compute_dtype=None, remat=None, input_transforms=None,
                 grad_sync=None, step_guard=None,
                 max_consecutive_bad_steps=None, plan=None):
        import jax
        from ..base import get_env
        self.symbol = symbol
        self.mesh = mesh
        # mxplan consumption (parallel/planner.py): a ShardingPlan (or
        # its plain doc) supplies the POLICY — grad_sync, sharding
        # rules, compute dtype — instead of ad-hoc arguments; explicit
        # arguments still win.  Derived artifacts (per-param specs,
        # gather groups) are recomputed at bind() for THIS mesh, so a
        # plan written at another world size consumes cleanly (the
        # elastic-resume contract).
        self._given_plan = None
        self.sharding_plan = None   # descriptive plan, built at bind()
        if plan is not None:
            from .planner import ShardingPlan
            if isinstance(plan, dict):
                plan = ShardingPlan.from_doc(plan)
            self._given_plan = plan
            if grad_sync is None:
                grad_sync = plan.grad_sync
            if param_shardings is None and plan.param_shardings:
                param_shardings = plan.param_shardings
            if compute_dtype is None and plan.compute_dtype:
                compute_dtype = plan.compute_dtype
        # Gradient synchronization over the dp axis:
        #   'allreduce' — replicated params; GSPMD psums grads (the
        #     reference's dist_sync allreduce, kvstore_dist.h).
        #   'zero' — master params + optimizer state SHARDED over dp
        #     (ZeRO/FSDP-style weight-sharded data parallelism, the
        #     scaling-book recipe): the step all-gathers params at its
        #     start (per-param AGs overlap early forward compute under
        #     XLA's latency-hiding scheduler), reduce-scatters each
        #     gradient as it is produced during backward, and updates
        #     only the local 1/dp shard.  Halves the comm on the backward
        #     critical path vs allreduce and cuts optimizer-state HBM by
        #     dp; numerics are identical (tests/test_parallel.py).
        #     MULTI-PROCESS CAVEAT: under 'zero' every param is sharded,
        #     so get_params/get_states/save_checkpoint become COLLECTIVE
        #     (cross-process AllGather) — all ranks must call them
        #     together.  Rank-guarded checkpointing (the reference's
        #     rank-0-only idiom, safe under 'allreduce' because
        #     replicated values are read locally) would deadlock; gather
        #     on every rank, then write from rank 0 only.
        #   'zero3' — fully sharded (ZeRO-3/FSDP): same sharded master
        #     params + optimizer state as 'zero', but the step gathers
        #     each parameter GROUP on demand (group boundaries keyed by
        #     the executor plan's topological order; planner-derived
        #     buckets under MXTPU_ZERO3_GATHER_GROUP=auto, manual
        #     N-layers-per-group otherwise), the backward RE-GATHERS
        #     instead of keeping replicated copies alive across the
        #     fwd/bwd boundary (jax.checkpoint policy dropping the
        #     tagged gathers), and gradients leave the backward as
        #     reduce-scatter.  Two tiers (parallel/zero3.py): a manual
        #     shard_map formulation on pure-dp meshes whose collective
        #     schedule is guaranteed on every backend, and a GSPMD
        #     formulation on multi-axis meshes (dp x tp/ep/pp
        #     composition).  trainer.analyze()'s
        #     graph-collective-schedule rule PROVES the compiled
        #     schedule matches the declaration.
        if grad_sync is None:
            grad_sync = get_env(ENV_GRAD_SYNC, "allreduce")
        if grad_sync not in ("allreduce", "zero", "zero3"):
            raise MXNetError(
                "grad_sync must be 'allreduce', 'zero' or 'zero3', "
                "got %r" % (grad_sync,))
        self.grad_sync = grad_sync
        # _zero: sharded-master placement (zero AND zero3 share the
        # _param_spec machinery and the gathering eval path)
        self._zero = grad_sync in ("zero", "zero3") and mesh is not None \
            and mesh.shape.get(data_axis, 1) > 1
        self._zero3 = grad_sync == "zero3" and self._zero
        self.zero3_tier = None      # set at bind(): 'manual' | 'gspmd'
        self._zero3_dims = {}       # param -> dp-sharded dim index
        self._zero3_groups = []     # topo-ordered gather groups
        # remat/mirror: rematerialize the forward inside the backward
        # (reference MXNET_BACKWARD_DO_MIRROR memory mode)
        if remat is None:
            from ..executor import ENV_BACKWARD_DO_MIRROR
            remat = str(get_env(ENV_BACKWARD_DO_MIRROR, "0")) == "1"
        self.remat = bool(remat)
        # a mesh spanning several processes (multi-host cluster joined via
        # distributed.initialize) switches placement to the global-array
        # path: each process contributes its local batch shard and holds a
        # replica of every parameter
        self._multiproc = mesh is not None and any(
            d.process_index != jax.process_index()
            for d in mesh.devices.flat)
        self.data_axis = data_axis
        # On-device input preprocessing, compiled into the fused step: maps
        # input name -> jax-traceable fn.  The TPU-first feed path sends raw
        # uint8 NHWC batches over the (slow) host link and does
        # normalize/transpose/cast here, where they fuse into the first
        # conv for free (the reference instead normalizes on the host in
        # its C++ iterator, src/io/iter_normalize.h).  bind() shapes refer
        # to the POST-transform (symbol-visible) shapes.
        self.input_transforms = dict(input_transforms or {})
        self.param_shardings = param_shardings or {}
        self.compute_dtype = compute_dtype and np.dtype(compute_dtype)
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        kind = type(optimizer).__name__.lower()
        if kind not in SUPPORTED_OPTIMIZERS:
            raise MXNetError(
                "SPMDTrainer: in-graph rule for optimizer %r not implemented "
                "(sgd/adam/rmsprop supported); use mx.mod.Module for other "
                "optimizers" % kind)
        self.optimizer = optimizer
        from ..executor import mirror_segments_for
        self._eval = _build_eval(
            symbol,
            mirror_segments=mirror_segments_for(symbol, force=self.remat))
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        # NaN/Inf step guard: an in-graph all-finite check over the raw
        # gradients; a non-finite step applies NO update (params, aux and
        # optimizer state pass through unchanged inside the same fused
        # program).  Skip accounting is ALSO in-graph: the step carries a
        # donated (total_skips, consecutive_bad) i32 pair, so the host
        # never needs a per-step device sync to know how many updates were
        # dropped.  The counters are read ONE STEP LATE by default (at the
        # next step()'s entry, or at flush_step_guard/get_params/counter
        # reads) — a one-deep pipeline — and when deferred metrics raise
        # ``flush_interval`` above 1, only every that-many steps (at most
        # ``flush_interval`` steps of staleness; counter-property reads
        # always flush and are exact).  After ``max_consecutive_bad_steps``
        # bad steps in a row the flush aborts with MXNetError — persistent
        # NaNs mean a diverged model, and silently skipping forever would
        # burn a pod doing nothing.
        from ..resilience import ENV_STEP_GUARD, ENV_MAX_BAD_STEPS
        if step_guard is None:
            step_guard = str(get_env(ENV_STEP_GUARD, "1")) != "0"
        self.step_guard = bool(step_guard)
        if max_consecutive_bad_steps is None:
            max_consecutive_bad_steps = int(
                get_env(ENV_MAX_BAD_STEPS, "10"))
        self.max_consecutive_bad_steps = int(max_consecutive_bad_steps)
        self._skipped_steps = 0           # total guarded skips, ever
        self._consecutive_bad_steps = 0   # current bad-step run length
        self._skip_base = 0               # host total when counters placed
        self._guard_acc = None            # device (total, consec, trips) i32
        self._guard_pending = False       # unread counters in flight
        self._trips_seen = 0              # abort events already raised
        self.last_step_skipped = False    # most recently FLUSHED step
        # deferred in-graph metrics: optional (sum, count) f32 accumulators
        # carried through the donated step (install_metric); fetch_metric
        # reads them and re-zeroes, so each accumulation window spans at
        # most flush_interval steps and f32 stays exact for integer sums
        self._metric_fn = None
        self._metric_key = None
        self._metric_acc = None
        # host<->device sync cadence for the guard counters: 1 = flush at
        # every step entry (classic one-deep pipeline); >1 = flush every
        # N steps (set by install_metric for deferred-metric runs)
        self.flush_interval = 1
        self._steps_since_flush = 0

        # optional hung-step watchdog (resilience.StepWatchdog): when set
        # (fit() wires it through install_watchdog), every fused-step
        # dispatch+sync is armed so a wedged collective aborts the
        # process with a stack dump instead of hanging the pod silently
        self.watchdog = None
        self._rep_fn = None       # cached jitted reshard-to-replicated
        self.params = None        # dict name -> jax array (sharded)
        self.aux = None
        self.opt_state = None
        self._num_update = 0
        self._step_fn = None
        self._eval_fn = None
        self._outputs = None

    # -- setup ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None):
        data_shapes = [d if isinstance(d, DataDesc) else DataDesc(d[0], d[1])
                       for d in data_shapes]
        label_shapes = [l if isinstance(l, DataDesc) else DataDesc(l[0], l[1])
                        for l in (label_shapes or [])]
        self.data_names = [d.name for d in data_shapes]
        self.label_names = [l.name for l in label_shapes]
        self.input_names = self.data_names + self.label_names
        unknown_tf = set(self.input_transforms) - set(self.input_names)
        if unknown_tf:
            raise MXNetError(
                "input_transforms keys %s are not bound inputs %s"
                % (sorted(unknown_tf), self.input_names))
        shapes = {d.name: d.shape for d in data_shapes + label_shapes}
        arg_shapes, out_shapes, aux_shapes = self.symbol.infer_shape(**shapes)
        self.arg_shapes = dict(zip(self.arg_names, arg_shapes))
        self.aux_shapes = dict(zip(self.aux_names, aux_shapes))
        self.out_shapes = out_shapes
        self.param_names = [n for n in self.arg_names
                            if n not in self.input_names]
        self.batch_size = data_shapes[0].shape[0]
        # seed the per-name wd/lr multipliers now that param names are known
        # (zeroes wd for biases/gammas/betas like the reference's
        # set_wd_mult — the Module/kvstore path and this fused path must
        # apply identical decay)
        self.optimizer.idx2name = dict(enumerate(self.param_names))
        # seed name-based defaults (zero wd for biases/gammas/betas) without
        # wiping multipliers the user already set via set_lr_mult/set_wd_mult
        user_lr = dict(getattr(self.optimizer, "lr_mult", {}) or {})
        user_wd = dict(getattr(self.optimizer, "wd_mult", {}) or {})
        self.optimizer.set_wd_mult({})
        self.optimizer.set_lr_mult({})
        self.optimizer.lr_mult.update(user_lr)
        self.optimizer.wd_mult.update(user_wd)
        if self._zero3:
            self._plan_zero3()
        self._build_step()
        # the descriptive plan: what THIS trainer executes (world, mesh
        # axes, resolved per-param placement, gather groups).
        # save_checkpoint persists it in the manifest so a resume on a
        # different inventory knows the writing run's layout
        from .planner import ShardingPlan
        self.sharding_plan = ShardingPlan.from_trainer(self)
        return self

    def _plan_zero3(self):
        """Choose the zero3 tier and plan the gather groups (bind time).

        A parameter participates in the grouped gathers when its
        resolved spec shards EXACTLY the dp axis on one dimension
        (_param_spec's dp-derived shard or an explicit dp rule);
        explicit tp/ep/pp rules and indivisible params stay outside the
        groups (GSPMD handles the former, the latter remain replicated
        with plain psum gradients — correct either way).

        Tier: 'manual' (shard_map body, guaranteed all-gather/
        reduce-scatter schedule) needs a pure-dp mesh, a shard_map
        spelling, batch-leading outputs and at least one shardable
        param; anything else composes through the 'gspmd' tier.
        """
        from ..base import get_env
        from . import zero3 as z3
        from .zero3 import ENV_ZERO3_GATHER_GROUP
        from .compat import HAS_SHARD_MAP
        shardable = {}
        for name in self.param_names:
            spec = self._param_spec(name, self.arg_shapes[name])
            entries = tuple(spec)
            if not entries or any(
                    e not in (None, self.data_axis) for e in entries):
                continue
            dims = [i for i, e in enumerate(entries)
                    if e == self.data_axis]
            if len(dims) == 1:
                shardable[name] = dims[0]
        self._zero3_dims = shardable
        self._zero3_groups = self._choose_gather_groups(shardable)
        pure_dp = tuple(self.mesh.axis_names) == (self.data_axis,)
        batch_leading = all(s and s[0] == self.batch_size
                            for s in self.out_shapes)
        self.zero3_tier = "manual" if (
            pure_dp and HAS_SHARD_MAP and batch_leading and shardable
        ) else "gspmd"

    def _choose_gather_groups(self, shardable):
        """Gather groups for the zero3 step: under the
        ``MXTPU_ZERO3_GATHER_GROUP=auto`` default, a consumed plan's
        recorded groups when they match this bind exactly, otherwise
        the planner's first-consumer/bucket-merged grouping.  A NUMERIC
        env value is the operator's manual override and wins even over
        a consumed plan — warning when the planned grouping
        Pareto-dominates it on the memory model (fewer collectives AND
        a no-bigger replicated peak)."""
        import logging
        from ..base import get_env
        from . import planner
        from . import zero3 as z3
        from .zero3 import ENV_ZERO3_GATHER_GROUP
        names = sorted(shardable)
        if not names:
            return []
        comm_itemsize = self.compute_dtype.itemsize \
            if self.compute_dtype is not None else 4
        shapes = {n: tuple(self.arg_shapes[n]) for n in names}
        raw = str(get_env(ENV_ZERO3_GATHER_GROUP, "auto") or
                  "auto").strip().lower()
        given = self._given_plan
        if raw in ("", "auto") and given is not None and \
                given.gather_groups and \
                given.world == self.mesh.shape[self.data_axis] and \
                sorted(n for g in given.gather_groups for n in g) == names:
            return [list(g) for g in given.gather_groups]
        planned = planner.derive_gather_groups(
            self.symbol, names, shapes, itemsize=comm_itemsize)
        if raw in ("", "auto"):
            return planned
        try:
            group_layers = int(raw)
        except (TypeError, ValueError):
            logging.getLogger(__name__).warning(
                "MXTPU_ZERO3_GATHER_GROUP=%r is neither 'auto' nor an "
                "integer — using the planned grouping", raw)
            return planned
        manual = z3.plan_gather_groups(self.symbol, names, group_layers)
        sizes = {n: int(np.prod(shapes[n])) * comm_itemsize
                 for n in names}
        mc = planner.group_cost(manual, sizes)
        pc = planner.group_cost(planned, sizes)
        if planner.dominates(pc, mc):
            logging.getLogger(__name__).warning(
                "MXTPU_ZERO3_GATHER_GROUP=%d loses to the planned "
                "grouping on the memory model: manual = %d collectives "
                "/ %d peak gathered bytes, planned = %d / %d — unset "
                "the knob (or set it to 'auto') to take the planner's "
                "grouping", group_layers, mc[0], mc[1], pc[0], pc[1])
        return manual

    def init_params(self, initializer, arg_params=None, aux_params=None):
        from ..ndarray import zeros as nd_zeros
        params, aux = {}, {}
        for name in self.param_names:
            arr = nd_zeros(self.arg_shapes[name])
            if arg_params and name in arg_params:
                arr[:] = arg_params[name]
            elif initializer is not None:
                initializer(name, arr)
            params[name] = arr._data
        for name in self.aux_names:
            arr = nd_zeros(self.aux_shapes[name])
            if aux_params and name in aux_params:
                arr[:] = aux_params[name]
            elif initializer is not None:
                initializer(name, arr)
            aux[name] = arr._data
        if self.compute_dtype is not None:
            params = {k: v for k, v in params.items()}  # master stays f32
        self.params = self._place_params(params)
        self.aux = self._place_params(aux, aux=True)
        self.opt_state = self._init_opt_state()

    def _sharding(self, spec):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec)

    def _param_spec(self, name, shape):
        """PartitionSpec for a master param / optimizer-state slot.
        Explicit param_shardings rules (tp etc.) always win; under
        grad_sync='zero' otherwise-replicated params shard their first
        dp-divisible dimension over the dp axis (indivisible params stay
        replicated and fall back to plain allreduce — correct either
        way)."""
        spec = _spec_for(name, shape, self.param_shardings)
        if self._zero and spec == P():
            dp = self.mesh.shape[self.data_axis]
            for i, d in enumerate(shape):
                if d % dp == 0 and d >= dp:
                    axes = [None] * len(shape)
                    axes[i] = self.data_axis
                    return P(*axes)
        return spec

    def _place(self, host, spec):
        """Put one host array onto the mesh with the given spec (handles
        the no-mesh, single-process-mesh, and multi-process-mesh cases)."""
        if self.mesh is None:
            return jnp.asarray(host)
        if self._multiproc:
            host = np.asarray(host)
            return jax.make_array_from_callback(
                host.shape, self._sharding(spec),
                lambda idx, _v=host: _v[idx])
        return jax.device_put(host, self._sharding(spec))

    def _place_params(self, params, aux=False):
        if self.mesh is None:
            return dict(params)
        if self._multiproc:
            # rank 0's values win (the reference's init-push semantics:
            # servers keep the first worker's init, kvstore_dist.h Init);
            # each process then materializes its addressable pieces
            from jax.experimental import multihost_utils
            names = sorted(params)
            vals = multihost_utils.broadcast_one_to_all(
                tuple(np.asarray(params[n]) for n in names))
            params = dict(zip(names, vals))
        # aux (BN moving stats) stays on the plain spec: it is updated by
        # replicated forward statistics, not reduce-scattered gradients
        spec_of = (lambda n, s: _spec_for(n, s, self.param_shardings)) \
            if aux else self._param_spec
        return {name: self._place(v, spec_of(name, np.shape(v)))
                for name, v in params.items()}

    def _init_opt_state(self):
        """In-graph optimizer state, sharded like its parameter."""
        state = {}
        kind = type(self.optimizer).__name__.lower()
        for name in self.param_names:
            p = self.params[name]
            spec = self._param_spec(name, p.shape)
            if self._multiproc:
                z = lambda: jax.make_array_from_callback(
                    p.shape, self._sharding(spec),
                    lambda idx, _s=p.shape, _d=p.dtype:
                        np.zeros(_slice_shape(idx, _s), _d))
            elif self.mesh is not None:
                z = lambda: jax.device_put(jnp.zeros(p.shape, p.dtype),
                                           self._sharding(spec))
            else:
                z = lambda: jnp.zeros_like(p)
            if kind in ("sgd", "ccsgd") and \
                    getattr(self.optimizer, "momentum", 0.0):
                s = (z(),)
            elif kind == "adam":
                s = (z(), z())
            elif kind == "rmsprop":
                s = (z(),)
            else:
                s = ()
            state[name] = s
        return state

    # -- the fused step ----------------------------------------------------
    def _apply_update(self, name, p, g, s, lr, wd, t):
        """In-graph optimizer rule (same ops as the reference's server-side
        update, src/operator/tensor/optimizer_op.cc)."""
        from ..ops import tensor as T
        o = self.optimizer
        clip = o.clip_gradient if o.clip_gradient is not None else -1.0
        rescale = o.rescale_grad
        lr = lr * o.lr_mult.get(name, 1.0)
        wd = wd * o.wd_mult.get(name, 1.0)
        kind = type(o).__name__.lower()
        if kind in ("sgd", "ccsgd"):
            if s:
                w, m = T.sgd_mom_update(p, g, s[0], lr=lr,
                                        momentum=o.momentum, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip)
                return w, (m,)
            return T.sgd_update(p, g, lr=lr, wd=wd, rescale_grad=rescale,
                                clip_gradient=clip), ()
        if kind == "adam":
            coef1 = 1.0 - o.beta1 ** t
            coef2 = 1.0 - o.beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            w, mean, var = T.adam_update(p, g, s[0], s[1], lr=lr_t,
                                         beta1=o.beta1, beta2=o.beta2,
                                         epsilon=o.epsilon, wd=wd,
                                         rescale_grad=rescale,
                                         clip_gradient=clip)
            return w, (mean, var)
        if kind == "rmsprop":
            w, n = T.rmsprop_update(p, g, s[0], lr=lr, gamma1=o.gamma1,
                                    epsilon=o.epsilon, wd=wd,
                                    rescale_grad=rescale, clip_gradient=clip,
                                    clip_weights=-1.0)
            return w, (n,)
        raise MXNetError("SPMDTrainer: in-graph rule for optimizer %r not "
                         "implemented (sgd/adam/rmsprop supported)" % kind)

    def _build_step(self):
        eval_fn = self._eval
        compute_dtype = self.compute_dtype
        transforms = dict(self.input_transforms)

        def xform(data):
            if not transforms:
                return dict(data)
            return {k: (transforms[k](v) if k in transforms else v)
                    for k, v in data.items()}

        zero = self._zero
        rep = self._sharding(P()) if zero else None
        # explicitly rule-sharded params (tp etc.) KEEP their spec in
        # the "gathered" view: constraining them to replicated would
        # silently negate the rule's HBM win — only the dp-sharded
        # (zero-derived) params widen to replicated for the step, and
        # the decision is recorded in plan.decisions
        gathered_spec = {}
        if zero:
            for name in self.param_names:
                spec = _spec_for(name, self.arg_shapes[name],
                                 self.param_shardings)
                gathered_spec[name] = self._sharding(spec) \
                    if tuple(spec) else rep

        def cast(p):
            if compute_dtype is None:
                return p
            return {k: v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in p.items()}

        def step(params, aux, opt_state, extras, data, rng, lr, wd, t):
            raw_data = data  # pre-transform inputs (labels for metrics)
            data = xform(data)
            if zero:
                # cast the dp-sharded f32 master to compute dtype BEFORE
                # gathering, so the per-param AllGathers (which the
                # latency-hiding scheduler overlaps with early forward
                # compute) move bf16 bytes, not the f32 master — the
                # FSDP mixed-precision comm discipline.  The cast output
                # is pinned to the SHARD spec so the partitioner cannot
                # hoist the gather above the convert (which would double
                # the gathered bytes).
                full = {}
                for k, v in cast(params).items():
                    v = jax.lax.with_sharding_constraint(
                        v, self._sharding(self._param_spec(k, v.shape)))
                    full[k] = jax.lax.with_sharding_constraint(
                        v, gathered_spec[k])
            else:
                full = params

            def loss_fn(p):
                if not zero:
                    p = cast(p)
                merged = dict(data)
                merged.update(p)
                outs, auxu = eval_fn(merged, aux, rng, True)
                return tuple(outs), auxu

            outs, vjp_fn, auxu = jax.vjp(loss_fn, full, has_aux=True)
            heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
            grads, = vjp_fn(heads)
            if zero:
                # constrain each gradient (still compute dtype) to its
                # param's dp shard: GSPMD lowers the batch-psum + shard
                # slice to a ReduceScatter issued as soon as the grad
                # exists during backward
                grads = {name: jax.lax.with_sharding_constraint(
                    g, self._sharding(self._param_spec(name, g.shape)))
                    for name, g in grads.items()}
            return self._step_tail(params, aux, opt_state, extras,
                                   raw_data, outs, auxu, grads,
                                   lr, wd, t)

        def eval_step(params, aux, data, rng, is_train=False):
            if zero:
                # same comm discipline as step(): cast the shard to
                # compute dtype (pinned to shard space) BEFORE the
                # gather, so eval AGs also move bf16 bytes
                full = {}
                for k, v in cast(params).items():
                    v = jax.lax.with_sharding_constraint(
                        v, self._sharding(self._param_spec(k, v.shape)))
                    full[k] = jax.lax.with_sharding_constraint(
                        v, gathered_spec[k])
                params = full
            elif compute_dtype is not None:
                params = cast(params)
            merged = xform(data)
            merged.update(params)
            outs, _ = eval_fn(merged, aux, rng, is_train)
            return outs

        # input shardings propagate from the placed arguments (params were
        # device_put with their NamedShardings, batches are sharded in
        # _shard_batch) — GSPMD partitions the step and inserts collectives.
        # Donation lets params/opt-state (and the guard/metric carries in
        # ``extras``) update in place in HBM.
        if self._zero3:
            # fully-sharded step: grouped on-demand gathers + backward
            # re-gather + reduce-scatter grads (parallel/zero3.py); the
            # eval path above already gathers via the shared zero branch
            step = self._make_zero3_step(xform, cast)
        self._step_raw = step  # analyzers make_jaxpr the unjitted step
        self._step_fn = jax.jit(step, donate_argnums=self.DONATE_ARGNUMS)
        self._eval_fn = jax.jit(eval_step, static_argnums=(4,))
        # MXTPU_ANALYZE bookkeeping: jit compiles one program PER input
        # shape signature (a partial final batch retraces), and every
        # compiled program gets its own lint — keyed by signature, not a
        # single bool, so strict mode cannot be bypassed by a shape
        # variant.  _analyze_off caches "env says no" after the first
        # look so the steady-state step pays one attribute check.
        self._analyzed_keys = set()
        self._analyze_off = False

    def _step_tail(self, params, aux, opt_state, extras, raw_data, outs,
                   auxu, grads, lr, wd, t, finite_reduce=None,
                   metric_reduce=None, aux_reduce=None):
        """Shared epilogue of EVERY fused-step flavor (allreduce / zero
        / zero3 both tiers): the all-finite guard over the finalized
        gradients, the in-graph optimizer update, aux merge, the
        stacked i32[3] skip counters and deferred-metric accumulation.
        One copy on purpose — the guard-carry layout and skip
        accounting were already reshaped once (the i32[3] stack) and
        must never drift between step flavors.

        The zero3 manual tier runs this inside a shard_map body and
        passes reducers that agree per-shard values across devices:
        ``finite_reduce`` (psum-AND of the finite flag — each device
        only checked its shard), ``metric_reduce`` (psum the local
        metric deltas — each device saw only its rows) and
        ``aux_reduce`` (pmean the per-device BN stats — the
        reference's multi-GPU batch-stat semantics, averaged)."""
        guard = self.step_guard
        metric_fn = self._metric_fn
        maxbad = self.max_consecutive_bad_steps
        finite = None
        if guard:
            # all-finite over every gradient, folded into the same XLA
            # program (one fused reduction tree) — the in-graph analog
            # of DynamicLossScale / Orbax-era skip-step guards
            finite = jnp.asarray(True)
            for name in self.param_names:
                finite = jnp.logical_and(
                    finite, jnp.all(jnp.isfinite(grads[name])))
            if finite_reduce is not None:
                finite = finite_reduce(finite)
        new_params, new_state = {}, {}
        for name in self.param_names:
            g = grads[name].astype(params[name].dtype)
            w, s = self._apply_update(name, params[name], g,
                                      opt_state[name], lr, wd, t)
            if guard:
                # non-finite step: params AND optimizer state pass
                # through unchanged (selects fuse into the update)
                w = jnp.where(finite, w, params[name])
                s = tuple(jnp.where(finite, sn, so)
                          for sn, so in zip(s, opt_state[name]))
            new_params[name] = w
            new_state[name] = s
        new_aux = dict(aux)
        new_aux.update(auxu)
        new_extras = {}
        if guard:
            # BN moving stats computed from a poisoned batch must not
            # stick either
            for name, v in auxu.items():
                new_aux[name] = jnp.where(finite, v, aux[name])
        if aux_reduce is not None:
            for name in auxu:
                new_aux[name] = aux_reduce(new_aux[name])
        if guard:
            # in-graph skip accounting: totals accumulate, the
            # consecutive run resets on any good step, and ``trips``
            # counts runs REACHING the abort threshold — so a bad run
            # that ends between two deferred flushes still aborts at
            # the next flush (the peak would otherwise be lost when
            # consec resets).  The host reads the counters lazily
            # (flush_step_guard), never per-step — and they travel
            # as ONE stacked i32[3] carry so each flush costs a
            # single device->host transfer, not three (three scalar
            # fetches were measurable per-step host work on the
            # dispatch-bound LSTM path over a high-RTT device link).
            g = extras["guard"]
            total, consec, trips = g[0], g[1], g[2]
            new_consec = jnp.where(finite, jnp.zeros_like(consec),
                                   consec + 1)
            if maxbad > 0:
                trips = trips + (new_consec == maxbad).astype(
                    trips.dtype)
            new_extras["guard"] = jnp.stack(
                [jnp.where(finite, total, total + 1), new_consec,
                 trips])
        if metric_fn is not None:
            # in-graph metric accumulation from this step's own
            # outputs and (pre-transform) labels; a guard-skipped
            # step contributes nothing — EXACT parity with the
            # blocking host path, which drops skipped steps too
            msum, mcnt = extras["metric"]
            ds, dc = metric_fn(list(outs), raw_data)
            if metric_reduce is not None:
                ds = metric_reduce(ds)
                dc = metric_reduce(dc)
            if guard:
                ds = jnp.where(finite, ds, jnp.zeros_like(ds))
                dc = jnp.where(finite, dc, jnp.zeros_like(dc))
            new_extras["metric"] = (msum + ds, mcnt + dc)
        return new_params, new_aux, new_state, new_extras, list(outs)

    def _make_zero3_step(self, xform, cast):
        """The grad_sync='zero3' fused step (both tiers).

        The gathers live INSIDE the loss closure and the vjp is taken
        with respect to the SHARDS, so the gather's autodiff transpose
        carries the gradients back: under the manual tier
        ``all_gather``'s transpose IS ``psum_scatter`` (reduce-scatter
        by construction); under the gspmd tier the shard constraint's
        transpose re-pins the cotangent to the shard spec and GSPMD
        places the reduction.  The whole closure runs under the zero3
        remat policy: every residual checkpoints normally EXCEPT the
        tagged gathered parameters, which the backward re-gathers —
        nothing replicated survives the fwd/bwd boundary, so peak
        parameter residency stays ~1/world plus one gather group.
        """
        import jax
        from . import zero3 as z3
        eval_fn = self._eval
        param_names = tuple(self.param_names)
        manual = self.zero3_tier == "manual"
        axis = self.data_axis
        dp = self.mesh.shape[axis]
        policy = z3.remat_policy()
        shard_dim = dict(self._zero3_dims)
        groups = [list(g) for g in self._zero3_groups]
        grouped = frozenset(n for g in groups for n in g)

        if manual:
            gather_grouped = z3.make_manual_gather(
                groups, shard_dim,
                {n: tuple(self.arg_shapes[n]) for n in grouped}, dp, axis)
        else:
            gather_grouped = z3.make_gspmd_gather(
                groups,
                lambda n: self._sharding(
                    self._param_spec(n, self.arg_shapes[n])),
                self._sharding(P()))

        def step(params, aux, opt_state, extras, data, rng, lr, wd, t):
            raw_data = data
            data = xform(data)
            if manual:
                # decorrelate per-device stochastic draws (Dropout):
                # each dp shard folds its axis index so masks are
                # independent across the global batch, deterministic
                # per seed
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def loss_fn(p):
                cp = cast(p)
                full = dict(cp)
                full.update(gather_grouped({n: cp[n] for n in grouped}))
                merged = dict(data)
                merged.update(full)
                outs, auxu = eval_fn(merged, aux, rng, True)
                return tuple(outs), auxu

            loss_ck = jax.checkpoint(loss_fn, policy=policy)
            outs, vjp_fn, auxu = jax.vjp(loss_ck, params, has_aux=True)
            heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
            grads, = vjp_fn(heads)
            if manual:
                # grouped params arrived REDUCE-SCATTERED (all_gather's
                # transpose); ungrouped (replicated) params hold local
                # partials — psum them (tiny residue: indivisible dims)
                grads = {n: (g if n in grouped
                             else jax.lax.psum(g, axis))
                         for n, g in grads.items()}
            else:
                grads = {n: jax.lax.with_sharding_constraint(
                    g, self._sharding(self._param_spec(n, g.shape)))
                    for n, g in grads.items()}
            return self._step_tail(
                params, aux, opt_state, extras, raw_data, outs, auxu,
                grads, lr, wd, t,
                # manual tier: agree per-shard values across the
                # shard_map body (each device checked/saw only its
                # shard/rows; pmean'd BN stats are the reference's
                # multi-GPU per-device-batch semantics, averaged —
                # docs/how_to/sharded_training.md)
                finite_reduce=(lambda f: jax.lax.psum(
                    f.astype(jnp.int32), axis) >= dp) if manual else None,
                metric_reduce=(lambda v: jax.lax.psum(v, axis))
                if manual else None,
                aux_reduce=(lambda v: jax.lax.pmean(v, axis))
                if manual else None)

        if not manual:
            return step

        # manual tier: the body above runs per-device under shard_map —
        # every collective is explicit, so the schedule cannot depend on
        # backend partitioner heuristics
        from .compat import shard_map
        pspec = {n: (P(*[axis if i == shard_dim[n] else None
                         for i in range(len(self.arg_shapes[n]))])
                     if n in grouped else P())
                 for n in param_names}
        dspec = {}
        for name in self.input_names:
            ndim = len(self.arg_shapes.get(name, ())) or 1
            dspec[name] = P(axis, *([None] * (ndim - 1)))
        in_specs = (pspec, P(), pspec, P(), dspec, P(), P(), P(), P())
        out_specs = (pspec, P(), pspec, P(),
                     [P(axis, *([None] * (len(s) - 1)))
                      for s in self.out_shapes])
        return shard_map(step, self.mesh, in_specs, out_specs,
                         check_vma=False)

    # -- public API --------------------------------------------------------
    def stage_batch(self, *batch_arrays):
        """Place one batch (data+labels in ``input_names`` order) onto the
        mesh ahead of time: sharded device_put, compute-dtype cast, and
        the multihost global-array conversion — exactly what ``step``
        would do internally.  Returns the ``{name: device_array}`` dict a
        :class:`~mxnet_tpu.io.StagedBatch` carries; safe to call from a
        background thread (dataflow.DevicePrefetchIter), which is how the
        upload of batch N+1 overlaps the execution of batch N."""
        return self._shard_batch(batch_arrays)

    def _resolve_batch(self, batch_arrays):
        """Input dict for one step: a single StagedBatch short-circuits
        the transfer; raw arrays go through _shard_batch.  An armed
        poison_grad fault re-stages from the (poisoned) host copies so
        fault injection keeps working on the prefetched path."""
        from ..io import StagedBatch
        from ..resilience import faults
        if len(batch_arrays) == 1 and isinstance(batch_arrays[0],
                                                 StagedBatch):
            b = batch_arrays[0]
            if faults.is_armed("poison_grad"):
                arrays = self._poison_batch(
                    tuple(list(b.data) + list(b.label or [])))
                return self._shard_batch(arrays)
            return dict(b.staged)
        if faults.is_armed("poison_grad"):
            batch_arrays = self._poison_batch(batch_arrays)
        return self._shard_batch(batch_arrays)

    def _shard_batch(self, arrays):
        out = {}
        for name, v in zip(self.input_names, arrays):
            raw = v._data if isinstance(v, NDArray) else jnp.asarray(
                np.asarray(v))
            if self.compute_dtype is not None and \
                    jnp.issubdtype(raw.dtype, jnp.floating):
                raw = raw.astype(self.compute_dtype)
            spec = P(self.data_axis, *([None] * (raw.ndim - 1)))
            if self._multiproc:
                # this process's batch is one shard of the global batch
                # (the reference's per-worker minibatch, batch *= num_workers
                # scaling at the optimizer, module.py:461)
                from jax.experimental import multihost_utils
                raw = multihost_utils.host_local_array_to_global_array(
                    np.asarray(raw), self.mesh, spec)
            elif self.mesh is not None:
                raw = jax.device_put(raw, self._sharding(spec))
            out[name] = raw
        return out

    def _localize(self, outs):
        """In multi-process mode, return each output's process-local batch
        shard as a host array (workers see their own slice, exactly like the
        reference's per-worker executor outputs)."""
        if not self._multiproc:
            return outs
        from jax.experimental import multihost_utils
        dp = self.mesh.shape[self.data_axis]
        local = []
        for o in outs:
            # prefer the array's ACTUAL sharding over a shape heuristic: a
            # replicated output whose leading dim happens to divide dp must
            # not be sliced
            s = getattr(o, "sharding", None)
            if o.ndim == 0 or (s is not None and s.is_fully_replicated):
                spec = P()
            elif isinstance(s, NamedSharding):
                spec = s.spec
            elif o.shape[0] % dp == 0:
                spec = P(self.data_axis, *([None] * (o.ndim - 1)))
            else:
                spec = P()
            local.append(multihost_utils.global_array_to_host_local_array(
                o, self.mesh, spec))
        return local

    def _scalar_acc(self, value, dtype):
        """One replicated scalar accumulator on the mesh."""
        return self._place(np.asarray(value, dtype), P())

    def step(self, *batch_arrays, key=None):
        """One fused train step: data+labels in input_names order, or a
        single pre-placed :class:`~mxnet_tpu.io.StagedBatch` (from
        ``stage_batch``/``DevicePrefetchIter``) that skips the
        host->device transfer.

        ``key`` lets a caller that already previewed this step's outputs
        (module.get_outputs between forward and update) hand in the exact
        key so stochastic layers draw the same masks in both passes."""
        from contextlib import nullcontext
        from .. import random as _random
        from ..resilience import faults
        wd = self.watchdog
        with wd.armed("fused step %d" % (self._num_update + 1)) \
                if wd is not None else nullcontext():
            # deterministic hang injection (watchdog drill): stalls here,
            # inside the armed window, exactly like a wedged collective
            faults.maybe_hang("hang_step")
            return self._step_impl(batch_arrays, key)

    def _step_impl(self, batch_arrays, key):
        from .. import random as _random
        # consume the PREVIOUS steps' guard counters before dispatching
        # this one: a one-deep pipeline by default (the device runs step N
        # while the host preps N+1); with flush_interval > 1 (deferred
        # metrics) the read happens only every that-many steps
        self._steps_since_flush += 1
        if self._steps_since_flush >= max(1, self.flush_interval):
            self.flush_step_guard()
        if self._zero3:
            # the manual tier shard_maps the step and every tier
            # dp-shards the batch: an indivisible (unpadded final)
            # batch must fail with guidance BEFORE the placement layer
            # throws its own error (iterators pad by default).  Raw
            # arrays in a multi-process run are the LOCAL batch — the
            # global dim is local x processes, so the local rows only
            # need to cover this process's share of the dp axis; a
            # StagedBatch already holds GLOBAL arrays and checks
            # against the full axis.
            import jax
            from ..io import StagedBatch
            dp = self.mesh.shape[self.data_axis]
            arrays = batch_arrays
            need = dp
            if len(arrays) == 1 and isinstance(arrays[0], StagedBatch):
                arrays = tuple(arrays[0].staged.values())
            elif self._multiproc:
                need = max(1, dp // max(1, jax.process_count()))
            for v in arrays:
                n = np.shape(v)[0] if np.ndim(v) else 0
                if n % need:
                    raise MXNetError(
                        "grad_sync='zero3': batch dim %d does not "
                        "divide this process's share (%d) of the dp "
                        "axis (%d) — pad the final batch (iterator "
                        "default) or use grad_sync='zero'"
                        % (n, need, dp))
        data = self._resolve_batch(batch_arrays)
        self._num_update += 1
        lr = self.optimizer.lr if self.optimizer.lr_scheduler is None else \
            self.optimizer.lr_scheduler(self._num_update)
        if key is None:
            key = _random.next_key()
        extras = {}
        if self.step_guard:
            if self._guard_acc is None:
                self._guard_acc = self._scalar_acc(
                    np.zeros(3, np.int32), np.int32)
                self._trips_seen = 0
            extras["guard"] = self._guard_acc
        if self._metric_fn is not None:
            if self._metric_acc is None:
                self._metric_acc = (self._scalar_acc(0.0, np.float32),
                                    self._scalar_acc(0.0, np.float32))
            extras["metric"] = self._metric_acc
        args = (self.params, self.aux, self.opt_state, extras, data, key,
                jnp.asarray(lr, jnp.float32),
                jnp.asarray(self.optimizer.wd, jnp.float32),
                self._num_update)
        if not self._analyze_off:
            # MXTPU_ANALYZE: lint each newly compiled program (one per
            # input-shape signature) BEFORE its first dispatch — strict
            # mode must refuse to run a step that violates the graph
            # invariants, including a retraced partial-batch variant
            sig = tuple(sorted(
                (k, tuple(v.shape), str(getattr(v, "dtype", "")))
                for k, v in data.items()))
            if sig not in self._analyzed_keys:
                self._analyzed_keys.add(sig)
                self._maybe_env_analyze(args)
        self.params, self.aux, self.opt_state, extras, outs = \
            self._step_fn(*args)
        if self.step_guard:
            self._guard_acc = extras["guard"]
            self._guard_pending = True
        if self._metric_fn is not None:
            self._metric_acc = extras["metric"]
        outs = self._localize(outs)
        self._outputs = outs
        return outs

    def _poison_batch(self, batch_arrays):
        """Fault-injection hook: NaN out the first floating input so the
        step's gradients go non-finite deterministically (tier-1 coverage
        for the guard without waiting for a real divergence)."""
        from ..resilience import faults
        out = list(batch_arrays)
        for i, v in enumerate(out):
            host = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
            if np.issubdtype(host.dtype, np.floating):
                if faults.consume("poison_grad"):
                    out[i] = np.full_like(host, np.nan)
                break
        return tuple(out)

    @property
    def skipped_steps(self):
        """Total guard-skipped updates (flushes the in-flight flag)."""
        self.flush_step_guard()
        return self._skipped_steps

    @property
    def consecutive_bad_steps(self):
        """Current run of guard-skipped updates (flushes the in-flight
        flag)."""
        self.flush_step_guard()
        return self._consecutive_bad_steps

    def _read_scalar(self, v):
        """Host value of one replicated device scalar."""
        if self._multiproc:
            return np.asarray(v.addressable_shards[0].data)
        return np.asarray(v)

    def flush_step_guard(self):
        """Fold the in-graph skip counters into host state (blocks until
        the last dispatched step's program finished).  Called
        automatically at step() entry every ``flush_interval`` steps, at
        get_params/get_states, and by the counter properties — so counter
        reads are always exact; between reads the host may lag the device
        by at most ``flush_interval`` steps (deferred-metric mode).
        Raises the consecutive-bad-steps abort when the flushed run
        crosses the limit."""
        self._steps_since_flush = 0
        if not self._guard_pending:
            return
        self._guard_pending = False
        # ONE device->host fetch for all three counters (stacked i32[3])
        acc = np.asarray(self._read_scalar(self._guard_acc))
        total = int(acc[0]) + self._skip_base
        consec = int(acc[1])
        trips = int(acc[2])
        delta = total - self._skipped_steps
        self.last_step_skipped = consec > 0
        self._consecutive_bad_steps = consec
        if delta > 0:
            # those programs applied no update — roll the update counter
            # back so lr schedules and adam bias correction see only
            # applied steps (late by at most flush_interval steps under
            # the pipelined read; self-corrects here)
            self._num_update -= delta
            self._skipped_steps = total
            import logging
            logging.getLogger(__name__).warning(
                "step guard: non-finite gradients — %d update(s) skipped "
                "(%d consecutive, %d total)", delta,
                self._consecutive_bad_steps, self._skipped_steps)
        if trips > self._trips_seen and self.max_consecutive_bad_steps > 0:
            # a bad run reached the threshold since the last flush (the
            # in-graph trip counter latches runs whose peak fell between
            # deferred flushes); raise once per such run
            self._trips_seen = trips
            raise MXNetError(
                "step guard: %d consecutive steps produced non-finite "
                "gradients — model has diverged (raise MXTPU_MAX_BAD_STEPS "
                "or set MXTPU_STEP_GUARD=0 to disable the guard)"
                % self.max_consecutive_bad_steps)

    # -- deferred in-graph metrics ----------------------------------------
    def install_metric(self, graph_fn, flush_interval=0, key=None):
        """Fold a metric's (sum, count) accumulation INTO the fused step.

        ``graph_fn(outs, data) -> (sum, count)`` is a jax-traceable rule
        (see ``EvalMetric.graph_update``); the step then carries donated
        f32 accumulators and ``EvalMetric.update`` never needs a per-step
        device->host sync — the host fetches the running totals with
        :meth:`fetch_metric` every MXTPU_METRIC_INTERVAL steps / at epoch
        end.  Guard-skipped steps contribute nothing (exact parity with
        the blocking path, which drops them too).

        Installing (or removing with ``graph_fn=None``) rebuilds the step
        function — free before the first step, one recompile after;
        ``key`` identifies an equivalent rule (same metric type/labels/
        interval) so re-installing it — a second fit() with the same
        metric — skips the rebuild and keeps the compiled step.  The
        guard's ``flush_interval`` is raised alongside so the skip-counter
        read stops forcing a per-step sync (staleness is bounded by the
        same interval)."""
        if graph_fn is None and self._metric_fn is None:
            return  # nothing installed, nothing to remove
        if graph_fn is not None and key is not None and \
                key == self._metric_key:
            # same rule re-installed: keep the compiled step, just start
            # a fresh accumulation window
            self._metric_acc = None
            return
        self._metric_fn = graph_fn
        self._metric_key = key if graph_fn is not None else None
        self._metric_acc = None
        if graph_fn is not None:
            self.flush_interval = int(flush_interval) if flush_interval \
                and int(flush_interval) > 0 else DEFAULT_GUARD_FLUSH_INTERVAL
        else:
            self.flush_interval = 1
        self._build_step()

    def fetch_metric(self):
        """(sum, count) accumulated in-graph since the last fetch (a
        device->host read of two scalars; blocks on the last step), then
        re-zeroed — bounded windows keep f32 exact for integer sums."""
        if self._metric_acc is None:
            return 0.0, 0.0
        s = float(self._read_scalar(self._metric_acc[0]))
        c = float(self._read_scalar(self._metric_acc[1]))
        self._metric_acc = None  # fresh zeros at the next step
        return s, c

    def reset_metric(self):
        """Zero the in-graph accumulators (epoch start)."""
        self._metric_acc = None

    def _eval_batch(self, batch_arrays):
        """Eval-path input dict: accepts a StagedBatch (no poison-fault
        re-staging — fault consumption belongs to train steps only)."""
        from ..io import StagedBatch
        if len(batch_arrays) == 1 and isinstance(batch_arrays[0],
                                                 StagedBatch):
            return dict(batch_arrays[0].staged)
        return self._shard_batch(batch_arrays)

    def eval_step(self, *batch_arrays):
        from .. import random as _random
        data = self._eval_batch(batch_arrays)
        return self._localize(
            self._eval_fn(self.params, self.aux, data, _random.next_key()))

    def forward_only(self, *batch_arrays, key=None):
        """Train-mode forward WITHOUT the update, for output inspection
        between forward_backward() and update().  Pass the same ``key`` the
        deferred step() will consume so stochastic layers (Dropout) draw
        identical masks; with no key, a peeked key is used (training stream
        not advanced, but masks differ from the eventual step)."""
        from .. import random as _random
        data = self._eval_batch(batch_arrays)
        if key is None:
            key = _random.peek_key()
        return self._localize(
            self._eval_fn(self.params, self.aux, data, key, True))

    @property
    def outputs(self):
        return [NDArray._from_jax(o) for o in (self._outputs or [])]

    def _gather(self, v):
        if self._multiproc:
            # replicated values (the default) are readable locally with no
            # collective — critical for rank-guarded checkpointing, where a
            # cross-process reshard would deadlock the other ranks
            if v.sharding.is_fully_replicated:
                return np.asarray(v.addressable_shards[0].data)
            # genuinely sharded (tp/...): reshard to replicated (GSPMD
            # AllGather, cached per instance).  NOTE: collective — all
            # processes must call get_params/get_states together then.
            if self._rep_fn is None:
                self._rep_fn = jax.jit(lambda x: x,
                                       out_shardings=self._sharding(P()))
                if self._zero:
                    import logging
                    logging.getLogger(__name__).info(
                        "grad_sync=%r: gathering sharded params is a "
                        "COLLECTIVE — all ranks must call get_params/"
                        "get_states together (rank-guarded checkpointing "
                        "deadlocks; write from rank 0 AFTER the gather)"
                        % self.grad_sync)
            rep = self._rep_fn(v)
            out = np.asarray(rep.addressable_shards[0].data)
            # free the replicated device copy NOW: per-parameter
            # gathering bounds the device-side peak at shards + ONE
            # full param, instead of shards + the whole f32 master
            try:
                rep.delete()
            except Exception:  # noqa: BLE001 — best-effort release
                pass
            return out
        return jax.device_get(v)

    def _host_resident(self, host):
        """Wrap one gathered host array for get_params WITHOUT pushing
        it back through the default backend: on an accelerator backend
        the old ``jnp.asarray(host)`` re-uploaded the full f32 master —
        every parameter at once — into HBM, exactly the residency
        zero/zero3 sharding exists to avoid.  The NDArray stays pinned
        to the host platform; checkpoint/serialization paths only ever
        read it back with asnumpy()."""
        import jax
        if jax.default_backend() != "cpu":
            try:
                dev = jax.local_devices(backend="cpu")[0]
                return jax.device_put(np.asarray(host), dev)
            except RuntimeError:  # no host platform registered
                pass
        return jnp.asarray(np.asarray(host))

    def get_params(self):
        """Gather params/aux to host NDArrays (for checkpointing).
        Gathers run ONE PARAMETER AT A TIME (bounded peak memory under
        grad_sync='zero'/'zero3'; see _gather) and the results stay
        host-resident."""
        self.flush_step_guard()
        arg_params = {k: NDArray._from_jax(
            self._host_resident(self._gather(v)))
            for k, v in self.params.items()}
        aux_params = {k: NDArray._from_jax(
            self._host_resident(self._gather(v)))
            for k, v in self.aux.items()}
        return arg_params, aux_params

    def snapshot_params(self):
        """Checkpoint-ready host snapshots: ``(arg, aux)`` dicts of
        frozen ``resilience._HostSnapshot`` values, gathered per
        parameter (device peak stays bounded under sharded params) and
        deep-copied once — ``resilience.snapshot_params`` ADOPTS these
        without another copy, so an async save pays one host copy
        total instead of gather + NDArray + snapshot."""
        from ..resilience import _HostSnapshot
        self.flush_step_guard()
        arg = {k: _HostSnapshot(np.array(self._gather(v), copy=True))
               for k, v in self.params.items()}
        aux = {k: _HostSnapshot(np.array(self._gather(v), copy=True))
               for k, v in self.aux.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params):
        """Replace parameter values, keeping optimizer state (the
        Module.set_params contract).  Names missing from the given dicts
        keep their current values."""
        # account any in-flight guarded step against the OLD counters
        # before its parameters are replaced
        self.flush_step_guard()

        def _host(v):
            return v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)

        def _merged(current, names, given):
            out = {}
            for n in names:
                if given and n in given:
                    out[n] = _host(given[n])
                else:
                    out[n] = self._gather(current[n])
            return out

        self.params = self._place_params(
            _merged(self.params, self.param_names, arg_params))
        self.aux = self._place_params(
            _merged(self.aux, self.aux_names, aux_params), aux=True)

    def get_states(self):
        """Serialized optimizer state (the Updater.get_states analog —
        reference kvstore.save_optimizer_states / Updater serialization)."""
        self.flush_step_guard()
        import pickle
        host = {k: tuple(np.asarray(self._gather(x)) for x in s)
                for k, s in self.opt_state.items()}
        return pickle.dumps({"num_update": self._num_update,
                             "states": host})

    def set_states(self, blob):
        # restored state opens a fresh guard window: drop any pre-restore
        # counters (their skip accounting belongs to the discarded run)
        # and the consecutive-bad count, so a recovery attempt after an
        # abort gets the full MXTPU_MAX_BAD_STEPS budget again; the
        # lifetime skip total survives via the host base
        self._guard_pending = False
        self._guard_acc = None
        self._skip_base = self._skipped_steps
        self._consecutive_bad_steps = 0
        import pickle
        payload = pickle.loads(blob)
        if isinstance(payload, dict) and "states" in payload \
                and "num_update" in payload:
            states = payload["states"]
            self._num_update = payload["num_update"]
            # this format always records every param's slot tuple — a
            # param missing from it means the blob belongs to a
            # DIFFERENT model (save->resume drift); restoring would
            # silently keep stale optimizer state for that param
            missing = sorted(set(self.params) - set(states))
            if missing:
                raise MXNetError(
                    "optimizer-state blob has no entry for parameter(s) "
                    "%s — the checkpoint belongs to a different model "
                    "(param added between save and resume?)"
                    % ", ".join(missing))
        else:
            # Updater-format blob ({index_or_name: state}) saved by the
            # executor/kvstore path — convert so checkpoints resume across
            # the path boundary (reference Updater serialization)
            idx2name = getattr(self.optimizer, "idx2name", {}) or {}
            states = {}
            for k, v in payload.items():
                name = idx2name.get(k, k)
                if v is None:
                    states[name] = ()
                elif isinstance(v, (tuple, list)):
                    states[name] = tuple(np.asarray(x) for x in v)
                else:
                    states[name] = (np.asarray(v),)
        placed = {}
        for name, s in states.items():
            if name not in self.params:
                raise MXNetError(
                    "optimizer state for unknown parameter %r" % (name,))
            spec = self._param_spec(name, self.params[name].shape)
            placed[name] = tuple(self._place(x, spec) for x in s)
        self.opt_state = placed

    def save_checkpoint(self, manager, step, blocking=None):
        """Checkpoint params + optimizer state through a
        :class:`~mxnet_tpu.resilience.CheckpointManager`.  The gathers run
        on EVERY rank (collective under sharded params — see _gather's
        note); the manager then writes atomically on rank 0 (plus this
        rank's replica shards under MXTPU_CKPT_REPLICAS).

        ``blocking=None`` follows ``MXTPU_CKPT_ASYNC``: the async path
        stalls the step loop only for the gather + host snapshot, the
        background writer does serialize + fsync + manifest — drain with
        ``manager.wait()``.

        Sharded params (zero/zero3) checkpoint GATHER-ON-SAVE: per-
        parameter collective gathers feed host snapshots directly (one
        bounded copy, no full-model device re-upload), and ``restore``
        re-shards through ``set_params``'s normal placement — sharded
        and replicated runs restore each other's checkpoints freely.

        Under ``MXTPU_CKPT_SHARDED=1`` a zero/zero3 trainer instead
        writes SHARDED-NATIVE checkpoints (one verified blob per dp
        shard, no host gather at all — see
        :meth:`save_checkpoint_sharded`); such saves are blocking by
        design."""
        from ..base import get_env
        from ..resilience import ENV_CKPT_SHARDED, checkpoint_async
        if str(get_env(ENV_CKPT_SHARDED, "0")).strip().lower() in \
                ("1", "true", "yes", "on") and self._zero and \
                hasattr(manager, "save_sharded"):
            if self._multiproc:
                if not getattr(self, "_sharded_multiproc_warned", False):
                    self._sharded_multiproc_warned = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "MXTPU_CKPT_SHARDED=1: multi-process sharded-"
                        "native saves need a publish barrier between "
                        "peer blob writes and rank 0's manifest — "
                        "falling back to gather-on-save")
            else:
                if (blocking is False or
                        (blocking is None and checkpoint_async())) and \
                        not getattr(self, "_sharded_async_warned", False):
                    self._sharded_async_warned = True
                    import logging
                    logging.getLogger(__name__).info(
                        "MXTPU_CKPT_SHARDED=1: sharded-native saves are "
                        "blocking by design (the per-shard payloads "
                        "read live device buffers the async writer "
                        "must never race a donating step for)")
                return self.save_checkpoint_sharded(manager, step)
        arg_params, aux_params = self.snapshot_params()
        states = self.get_states()
        plan_doc = self.sharding_plan.to_doc() \
            if self.sharding_plan is not None else None
        return manager.save(step, self.symbol, arg_params, aux_params,
                            optimizer_states=states, blocking=blocking,
                            plan=plan_doc)

    def _sharded_ckpt_dims(self):
        """param -> dp-shard dim for the sharded-native checkpoint
        layout: the single dim ``_param_spec`` shards over the dp axis,
        or None for params that stay replicated / carry explicit
        non-dp rules (those travel whole, in shard 0's blob)."""
        dims = {}
        for name in self.param_names:
            spec = tuple(self._param_spec(
                name, self.arg_shapes[name]))
            ds = [i for i, e in enumerate(spec) if e == self.data_axis]
            dims[name] = ds[0] if len(ds) == 1 and all(
                e in (None, self.data_axis) for e in spec) else None
        return dims

    def _shard_slice(self, v, dim, k, world):
        """Host copy of shard ``k``'s slice of device array ``v`` along
        ``dim`` — read straight from the addressable shard that already
        holds it (zero device compute, O(P/world) host bytes); falls
        back to slicing the assembled array only when the on-device
        layout does not match the declared shard (e.g. a replicated
        value)."""
        per = v.shape[dim] // world
        start = k * per
        for s in v.addressable_shards:
            idx = s.index[dim]
            if (idx.start or 0) == start and \
                    (idx.stop is None or idx.stop == start + per):
                return np.array(np.asarray(s.data), copy=True)
        sl = [slice(None)] * v.ndim
        sl[dim] = slice(start, start + per)
        return np.array(np.asarray(self._gather(v))[tuple(sl)],
                        copy=True)

    def save_checkpoint_sharded(self, manager, step):
        """Sharded-native checkpoint: every dp shard of the master
        params + optimizer state is serialized as its OWN verified blob
        straight from the device shards — NO full-model host gather, so
        peak host bytes are one shard's O(P/world) instead of O(P).
        Params without a dp shard dim (indivisible, or explicit non-dp
        rules) and the aux states ride whole in shard 0's blob.

        ``restore`` reads such checkpoints through the normal path:
        the manager verifies + assembles full host arrays and
        ``set_params`` re-shards them onto THIS trainer's mesh — so
        elastic resume works at any world size, matching the blob
        count or not."""
        import pickle
        self.flush_step_guard()
        world = self.mesh.shape[self.data_axis]
        dims = self._sharded_ckpt_dims()
        plan_doc = self.sharding_plan.to_doc() \
            if self.sharding_plan is not None else None

        def payload(k):
            out = {"epoch": int(step), "shard": int(k),
                   "world": int(world), "dims": dims,
                   "num_update": self._num_update,
                   "args": {}, "opt": {}}
            for name, v in self.params.items():
                d = dims[name]
                if d is not None:
                    out["args"][name] = self._shard_slice(v, d, k, world)
                    out["opt"][name] = tuple(
                        self._shard_slice(x, d, k, world)
                        for x in self.opt_state[name])
                elif k == 0:
                    out["args"][name] = np.asarray(self._gather(v))
                    out["opt"][name] = tuple(
                        np.asarray(self._gather(x))
                        for x in self.opt_state[name])
            if k == 0:
                out["aux"] = {n: np.asarray(self._gather(v))
                              for n, v in self.aux.items()}
            return pickle.dumps(out, protocol=4)

        return manager.save_sharded(step, self.symbol, payload,
                                    world=world, plan=plan_doc)

    def restore(self, manager, epoch=None):
        """Resume params + optimizer state (+ step counter, inside the
        states blob) from the manager's newest — or given — checkpoint;
        returns the restored epoch.

        ELASTIC: the checkpoint may have been written at a DIFFERENT
        world size — gather-on-save params are full host arrays, so
        ``set_params``'s placement re-shards them onto THIS trainer's
        mesh (replicated<->sharded and shard<->shard alike), and the
        persisted :class:`~mxnet_tpu.parallel.planner.ShardingPlan` in
        the manifest records what wrote the bytes.  The param SET must
        match exactly: a parameter added or removed between save and
        resume raises with names (never a silent misload — use
        ``set_params`` directly for deliberate partial restores)."""
        from .planner import diff_param_sets
        _, arg_params, aux_params, states, epoch = manager.restore(epoch)
        problems = diff_param_sets(
            {n: {} for n in arg_params}, set(self.param_names))
        problems += diff_param_sets(
            {n: {} for n in aux_params}, set(self.aux_names),
            kind="aux state")
        if problems:
            raise MXNetError(
                "restore: checkpoint epoch %d does not match this "
                "model's parameter set:\n  %s\n(a param added/removed "
                "between save and resume — fix the symbol, or load "
                "deliberately with set_params)"
                % (epoch, "\n  ".join(problems)))
        saved_plan = None
        if hasattr(manager, "plan"):
            saved_plan = manager.plan(epoch)
        if saved_plan is not None and self.sharding_plan is not None:
            saved_world = int(saved_plan.get("world", 1))
            here = self.sharding_plan.world
            if saved_world != here:
                import logging
                logging.getLogger(__name__).info(
                    "elastic resume: checkpoint epoch %d was written at "
                    "world=%d (grad_sync=%r), restoring at world=%d — "
                    "params re-shard through set_params placement",
                    epoch, saved_world, saved_plan.get("grad_sync"),
                    here)
        self.set_params(arg_params, aux_params)
        if states is not None:
            self.set_states(states)
        return epoch

    # -- static analysis (mxlint graph level) ------------------------------
    def _expects_allgather(self):
        """Whether the declared sharding legitimately all-gathers: under
        grad_sync='zero' (or any non-replicated param, e.g. tp rules)
        the step gathers params by design; under plain dp 'allreduce'
        every all-gather is a regression."""
        if self.mesh is None:
            return False
        if self._zero:
            return True
        return any(
            self._param_spec(n, self.arg_shapes[n]) != P()
            for n in self.param_names)

    def _zero3_expected_gather_bytes(self):
        """Per-step forward gather traffic a CORRECT zero3 step must
        move: the full-size bytes (in the comm dtype — compute_dtype
        for floating params) of every otherwise-replicated param with a
        dp-divisible dimension.  Computed from the BASE sharding rules
        and shapes, never from ``_param_spec`` overrides — a subclass
        that sabotages the sharding cannot also lower the bar the
        schedule lint holds it to."""
        if not self._zero3:
            return None
        dp = self.mesh.shape[self.data_axis]
        total = 0
        for name in self.param_names:
            shape = self.arg_shapes[name]
            if _spec_for(name, shape, self.param_shardings) != P():
                continue
            if not any(d % dp == 0 and d >= dp for d in shape):
                continue
            dtype = np.dtype(self.params[name].dtype) \
                if self.params else np.dtype(np.float32)
            if self.compute_dtype is not None and \
                    np.issubdtype(dtype, np.floating):
                dtype = self.compute_dtype
            total += int(np.prod(shape)) * np.dtype(dtype).itemsize
        return total

    def _lint_args(self, args, min_donate_bytes=0):
        """Run the graph lint against this trainer's compiled step with
        the given (fully assembled) argument tuple."""
        import jax
        from ..analysis import graph_lint
        lowered = self._step_fn.lower(*args)
        closed = jax.make_jaxpr(self._step_raw)(*args)
        param_bytes = sum(
            int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
            for v in self.params.values())
        schedule = None
        if self._zero3:
            schedule = "zero3-" + (self.zero3_tier or "gspmd")
        # the compiled platform decides which schedule shapes are owed
        # (gspmd-tier reduce-scatter exists only where XLA's
        # ReduceScatterCreator runs — TPU/GPU pipelines)
        if self.mesh is not None:
            platform = next(iter(self.mesh.devices.flat)).platform
        else:
            platform = jax.default_backend()
        report = graph_lint.lint_lowered(
            lowered, closed_jaxpr=closed,
            compute_dtype=self.compute_dtype,
            param_bytes=param_bytes,
            expect_allgather=self._expects_allgather(),
            schedule=schedule,
            expect_gather_bytes=self._zero3_expected_gather_bytes(),
            platform=platform,
            min_donate_bytes=min_donate_bytes,
            # the step's carries live in args 0-3 (params/aux/opt_state/
            # extras) BY SIGNATURE — restricting the missing-donation
            # check to them keeps a data batch that happens to share an
            # output's shape/dtype (autoencoder reconstructions,
            # per-example losses) from being flagged as a carry
            carry_argnums=(0, 1, 2, 3))
        # plan-fusion-parity: the mxfuse rewrite this step was built
        # from must keep the plain-plan monitored path intact
        report.merge(graph_lint.audit_plan_fusion(self.symbol))
        return report

    def analyze(self, *batch_arrays, min_donate_bytes=0):
        """Lint the fused step against one example batch (raw arrays in
        ``input_names`` order, or a StagedBatch) and return the
        :class:`~mxnet_tpu.analysis.report.Report`.

        Checks: every param/opt-state/guard/metric carry is donated
        (``min_donate_bytes=0`` — in THIS step's signature every carry
        should be donated regardless of size), no host callbacks, the
        collective audit (``report.stats['collectives']`` carries
        count+bytes even when nothing flags — bench.py's ``analyze``
        metric reads it), and dtype drift under ``compute_dtype``.
        Traces and compiles the step once; with a warm persistent
        compile cache (MXTPU_COMPILE_CACHE) the XLA work is reused."""
        args = self._example_args(*batch_arrays)
        return self._lint_args(args, min_donate_bytes=min_donate_bytes)

    def _example_args(self, *batch_arrays):
        """The fully assembled argument tuple ``_step_fn`` would see for
        one batch — what ``analyze`` lints and what ``bench.py zero3``
        lowers for ``memory_analysis`` without dispatching a step."""
        from .. import random as _random
        if self._step_fn is None or self.params is None:
            raise MXNetError(
                "SPMDTrainer.analyze: bind() and init_params() first")
        data = self._eval_batch(batch_arrays)
        extras = {}
        if self.step_guard:
            extras["guard"] = self._guard_acc if self._guard_acc \
                is not None else self._scalar_acc(np.zeros(3, np.int32),
                                                  np.int32)
        if self._metric_fn is not None:
            extras["metric"] = self._metric_acc or (
                self._scalar_acc(0.0, np.float32),
                self._scalar_acc(0.0, np.float32))
        return (self.params, self.aux, self.opt_state, extras, data,
                _random.peek_key(),
                jnp.asarray(self.optimizer.lr, jnp.float32),
                jnp.asarray(self.optimizer.wd, jnp.float32),
                self._num_update + 1)

    def _maybe_env_analyze(self, args):
        """MXTPU_ANALYZE=1|strict: graph-lint the program the first
        dispatch is about to run.  Findings log as warnings; ``strict``
        raises instead, refusing to train on a step that leaks a host
        sync or an HBM copy into every iteration."""
        from ..base import get_env
        from ..analysis import ENV_ANALYZE
        mode = str(get_env(ENV_ANALYZE, "") or "").strip().lower()
        if mode in ("", "0", "off", "false", "no"):
            # cache the "off" answer: the per-step signature hashing and
            # env read are not worth paying when analysis is disabled
            self._analyze_off = True
            return
        import logging
        log = logging.getLogger(__name__)
        report = self._lint_args(args)
        if report.ok:
            log.info("MXTPU_ANALYZE: fused step is clean (%s)",
                     report.stats.get("collectives") or "no collectives")
            return
        if mode == "strict":
            raise MXNetError(
                "MXTPU_ANALYZE=strict: the fused step violates graph "
                "invariants:\n%s" % report.format_text())
        log.warning("MXTPU_ANALYZE: fused step has %d finding(s):\n%s",
                    len(report.findings), report.format_text())

    def install_watchdog(self, watchdog):
        """Arm ``watchdog`` (resilience.StepWatchdog) around every fused
        step, and give its hang report this trainer's mesh/step context.
        Pass None to detach (also clears the info hook — a stale closure
        would pin this trainer alive and stamp a later run's hang report
        with the wrong trainer's context)."""
        if watchdog is None and self.watchdog is not None:
            self.watchdog.info = None
        self.watchdog = watchdog
        if watchdog is not None:
            def _info(_self=self):
                mesh = _self.mesh
                return ("trainer: step %d, grad_sync=%r, mesh=%s" %
                        (_self._num_update, _self.grad_sync,
                         "none" if mesh is None else dict(mesh.shape)))
            watchdog.info = _info
        return watchdog

    # -- lifecycle --------------------------------------------------------
    def close(self):
        """Deterministically release this trainer's device memory and
        compiled programs so several models can live sequentially in one
        process (the reference frees executor pools in ~GraphExecutor;
        XLA buffers otherwise wait for Python GC, and a retained
        PjitFunction pins its executable and donated-buffer arena).
        Safe to call twice; the trainer is unusable afterwards."""
        import jax

        def _delete_tree(v):
            for leaf in jax.tree_util.tree_leaves(v):
                if isinstance(leaf, jax.Array):
                    try:
                        leaf.delete()
                    except Exception:  # noqa: BLE001 — already deleted
                        pass

        for attr in ("params", "aux", "opt_state", "_outputs",
                     "_guard_acc", "_metric_acc"):
            _delete_tree(getattr(self, attr, None))
            setattr(self, attr, None)
        self._guard_pending = False
        # drop the jitted callables (each owns its executable + caches)
        self._step_raw = None
        for attr in ("_step_fn", "_eval_fn", "_rep_fn"):
            fn = getattr(self, attr, None)
            if fn is not None and hasattr(fn, "clear_cache"):
                try:
                    fn.clear_cache()
                except Exception:  # noqa: BLE001
                    pass
            setattr(self, attr, None)
        self._eval = None
        import gc
        gc.collect()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
        return False
