"""SPMDTrainer — one fused, mesh-sharded training step.

This is the TPU-native execution path that replaces the reference's whole
per-batch machinery (executor fan-out per device + KVStore push/pull +
optimizer on server, SURVEY §3.1/§3.4): forward, backward, gradient
AllReduce and the optimizer update are ONE jit-compiled XLA program,
annotated with shardings over a named Mesh.  GSPMD partitions it and
inserts the collectives (psum of grads over 'dp', AllGather for 'tp'
weights, ...) — lowered onto ICI, with buffer donation so parameters
update in-place in HBM.

Numerics match the reference's dist_sync protocol: grads are summed over
the dp axis and rescaled by 1/global_batch, then the optimizer rule (the
same sgd_update/adam_update ops the reference's server runs) applies once.
"""
from __future__ import annotations

import math
import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import optimizer as opt_mod
from ..base import MXNetError
from ..executor import _build_eval
from ..ndarray import NDArray
from ..io import DataDesc

__all__ = ["SPMDTrainer"]


def _spec_for(name, shape, rules):
    """Resolve a parameter's PartitionSpec from regex rules; default
    replicated."""
    for pattern, spec in (rules or {}).items():
        if re.match(pattern, name):
            spec = P(*spec) if not isinstance(spec, P) else spec
            if len(spec) > len(shape):
                raise MXNetError(
                    "sharding spec %s has more axes than param %s%s"
                    % (spec, name, shape))
            return spec
    return P()


class SPMDTrainer(object):
    """Fused sharded training step for a Symbol + Optimizer."""

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="dp", param_shardings=None,
                 compute_dtype=None):
        import jax
        self.symbol = symbol
        self.mesh = mesh
        self.data_axis = data_axis
        self.param_shardings = param_shardings or {}
        self.compute_dtype = compute_dtype and np.dtype(compute_dtype)
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        kind = type(optimizer).__name__.lower()
        if kind not in ("sgd", "ccsgd", "adam", "rmsprop"):
            raise MXNetError(
                "SPMDTrainer: in-graph rule for optimizer %r not implemented "
                "(sgd/adam/rmsprop supported); use mx.mod.Module for other "
                "optimizers" % kind)
        self.optimizer = optimizer
        self._eval = _build_eval(symbol)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.params = None        # dict name -> jax array (sharded)
        self.aux = None
        self.opt_state = None
        self._num_update = 0
        self._step_fn = None
        self._eval_fn = None
        self._outputs = None

    # -- setup ------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None):
        data_shapes = [d if isinstance(d, DataDesc) else DataDesc(d[0], d[1])
                       for d in data_shapes]
        label_shapes = [l if isinstance(l, DataDesc) else DataDesc(l[0], l[1])
                        for l in (label_shapes or [])]
        self.data_names = [d.name for d in data_shapes]
        self.label_names = [l.name for l in label_shapes]
        self.input_names = self.data_names + self.label_names
        shapes = {d.name: d.shape for d in data_shapes + label_shapes}
        arg_shapes, out_shapes, aux_shapes = self.symbol.infer_shape(**shapes)
        self.arg_shapes = dict(zip(self.arg_names, arg_shapes))
        self.aux_shapes = dict(zip(self.aux_names, aux_shapes))
        self.out_shapes = out_shapes
        self.param_names = [n for n in self.arg_names
                            if n not in self.input_names]
        self.batch_size = data_shapes[0].shape[0]
        # seed the per-name wd/lr multipliers now that param names are known
        # (zeroes wd for biases/gammas/betas like the reference's
        # set_wd_mult — the Module/kvstore path and this fused path must
        # apply identical decay)
        self.optimizer.idx2name = dict(enumerate(self.param_names))
        self.optimizer.set_wd_mult({})
        self.optimizer.set_lr_mult({})
        self._build_step()
        return self

    def init_params(self, initializer, arg_params=None, aux_params=None):
        from ..ndarray import zeros as nd_zeros
        params, aux = {}, {}
        for name in self.param_names:
            arr = nd_zeros(self.arg_shapes[name])
            if arg_params and name in arg_params:
                arr[:] = arg_params[name]
            elif initializer is not None:
                initializer(name, arr)
            params[name] = arr._data
        for name in self.aux_names:
            arr = nd_zeros(self.aux_shapes[name])
            if aux_params and name in aux_params:
                arr[:] = aux_params[name]
            elif initializer is not None:
                initializer(name, arr)
            aux[name] = arr._data
        if self.compute_dtype is not None:
            params = {k: v for k, v in params.items()}  # master stays f32
        self.params = self._place_params(params)
        self.aux = self._place_params(aux, aux=True)
        self.opt_state = self._init_opt_state()

    def _sharding(self, spec):
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec)

    def _place_params(self, params, aux=False):
        if self.mesh is None:
            return dict(params)
        out = {}
        for name, v in params.items():
            spec = _spec_for(name, v.shape, self.param_shardings)
            out[name] = jax.device_put(v, self._sharding(spec))
        return out

    def _init_opt_state(self):
        """In-graph optimizer state, sharded like its parameter."""
        state = {}
        kind = type(self.optimizer).__name__.lower()
        for name in self.param_names:
            p = self.params[name]
            z = lambda: jnp.zeros_like(p)
            if kind in ("sgd", "ccsgd") and \
                    getattr(self.optimizer, "momentum", 0.0):
                s = (z(),)
            elif kind == "adam":
                s = (z(), z())
            elif kind == "rmsprop":
                s = (z(),)
            else:
                s = ()
            if self.mesh is not None:
                spec = _spec_for(name, p.shape, self.param_shardings)
                s = tuple(jax.device_put(x, self._sharding(spec)) for x in s)
            state[name] = s
        return state

    # -- the fused step ----------------------------------------------------
    def _apply_update(self, name, p, g, s, lr, wd, t):
        """In-graph optimizer rule (same ops as the reference's server-side
        update, src/operator/tensor/optimizer_op.cc)."""
        from ..ops import tensor as T
        o = self.optimizer
        clip = o.clip_gradient if o.clip_gradient is not None else -1.0
        rescale = o.rescale_grad
        lr = lr * o.lr_mult.get(name, 1.0)
        wd = wd * o.wd_mult.get(name, 1.0)
        kind = type(o).__name__.lower()
        if kind in ("sgd", "ccsgd"):
            if s:
                w, m = T.sgd_mom_update(p, g, s[0], lr=lr,
                                        momentum=o.momentum, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip)
                return w, (m,)
            return T.sgd_update(p, g, lr=lr, wd=wd, rescale_grad=rescale,
                                clip_gradient=clip), ()
        if kind == "adam":
            coef1 = 1.0 - o.beta1 ** t
            coef2 = 1.0 - o.beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            w, mean, var = T.adam_update(p, g, s[0], s[1], lr=lr_t,
                                         beta1=o.beta1, beta2=o.beta2,
                                         epsilon=o.epsilon, wd=wd,
                                         rescale_grad=rescale,
                                         clip_gradient=clip)
            return w, (mean, var)
        if kind == "rmsprop":
            w, n = T.rmsprop_update(p, g, s[0], lr=lr, gamma1=o.gamma1,
                                    epsilon=o.epsilon, wd=wd,
                                    rescale_grad=rescale, clip_gradient=clip,
                                    clip_weights=-1.0)
            return w, (n,)
        raise MXNetError("SPMDTrainer: in-graph rule for optimizer %r not "
                         "implemented (sgd/adam/rmsprop supported)" % kind)

    def _build_step(self):
        eval_fn = self._eval
        param_names = tuple(self.param_names)
        compute_dtype = self.compute_dtype

        def step(params, aux, opt_state, data, rng, lr, wd, t):
            def loss_fn(p):
                if compute_dtype is not None:
                    p = {k: v.astype(compute_dtype) for k, v in p.items()}
                merged = dict(data)
                merged.update(p)
                outs, auxu = eval_fn(merged, aux, rng, True)
                return tuple(outs), auxu

            outs, vjp_fn, auxu = jax.vjp(loss_fn, params, has_aux=True)
            heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
            grads, = vjp_fn(heads)
            new_params, new_state = {}, {}
            for name in param_names:
                g = grads[name].astype(params[name].dtype)
                w, s = self._apply_update(name, params[name], g,
                                          opt_state[name], lr, wd, t)
                new_params[name] = w
                new_state[name] = s
            new_aux = dict(aux)
            new_aux.update(auxu)
            return new_params, new_aux, new_state, list(outs)

        def eval_step(params, aux, data, rng):
            if compute_dtype is not None:
                params = {k: v.astype(compute_dtype)
                          for k, v in params.items()}
            merged = dict(data)
            merged.update(params)
            outs, _ = eval_fn(merged, aux, rng, False)
            return outs

        # input shardings propagate from the placed arguments (params were
        # device_put with their NamedShardings, batches are sharded in
        # _shard_batch) — GSPMD partitions the step and inserts collectives.
        # Donation lets params/opt-state update in place in HBM.
        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))
        self._eval_fn = jax.jit(eval_step)

    # -- public API --------------------------------------------------------
    def _shard_batch(self, arrays):
        out = {}
        for name, v in zip(self.input_names, arrays):
            raw = v._data if isinstance(v, NDArray) else jnp.asarray(
                np.asarray(v))
            if self.compute_dtype is not None and \
                    jnp.issubdtype(raw.dtype, jnp.floating):
                raw = raw.astype(self.compute_dtype)
            if self.mesh is not None:
                raw = jax.device_put(raw, self._sharding(
                    P(self.data_axis, *([None] * (raw.ndim - 1)))))
            out[name] = raw
        return out

    def step(self, *batch_arrays):
        """One fused train step: data+labels in input_names order."""
        from .. import random as _random
        data = self._shard_batch(batch_arrays)
        self._num_update += 1
        lr = self.optimizer.lr if self.optimizer.lr_scheduler is None else \
            self.optimizer.lr_scheduler(self._num_update)
        self.params, self.aux, self.opt_state, outs = self._step_fn(
            self.params, self.aux, self.opt_state, data, _random.next_key(),
            jnp.asarray(lr, jnp.float32), jnp.asarray(self.optimizer.wd,
                                                      jnp.float32),
            self._num_update)
        self._outputs = outs
        return outs

    def eval_step(self, *batch_arrays):
        from .. import random as _random
        data = self._shard_batch(batch_arrays)
        return self._eval_fn(self.params, self.aux, data, _random.next_key())

    @property
    def outputs(self):
        return [NDArray._from_jax(o) for o in (self._outputs or [])]

    def get_params(self):
        """Gather params/aux to host NDArrays (for checkpointing)."""
        arg_params = {k: NDArray._from_jax(jax.device_get(v))
                      for k, v in self.params.items()}
        aux_params = {k: NDArray._from_jax(jax.device_get(v))
                      for k, v in self.aux.items()}
        return arg_params, aux_params
