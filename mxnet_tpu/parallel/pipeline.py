"""Pipeline parallelism over the 'pp' mesh axis — GPipe-style microbatch
schedule with neighbor exchange.

New capability beyond the reference (SURVEY §2.3: "Pipeline parallelism:
NO").  The idiomatic TPU formulation (scaling-book recipe): S homogeneous
stages hold their parameters stacked on a leading axis sharded over 'pp';
inside ``shard_map`` every device runs the same program, processes one
microbatch per tick, and passes activations to its ring neighbor with
``lax.ppermute`` (ICI).  A batch of M microbatches drains in M + S - 1
ticks — the classic pipeline bubble.

The reference's closest analog was manual layer placement across GPUs
(example/model-parallel-lstm); that overlapping-by-luck scheme becomes a
deterministic compiled schedule here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with a leading stage
    axis (shard it with PartitionSpec('pp', ...) on the mesh)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(fn, stage_params, x, mesh, axis_name="pp",
                   n_microbatch=None):
    """Run ``x`` through S pipelined stages of ``fn``.

    fn(params_of_one_stage, act) -> act         (shape-preserving)
    stage_params: pytree, leaves (S, ...), sharded P('pp', ...) over mesh
    x: (B, ...) replicated batch; B must divide by n_microbatch
    returns: (B, ...) replicated result of stage S-1 ∘ ... ∘ stage 0
    """
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    n_stages = mesh.shape[axis_name]
    n_given = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if n_given != n_stages:
        raise ValueError(
            "stage_params stack %d stages but mesh axis %r has %d devices "
            "(one stage per device; for more layers than devices, fold "
            "several layers into one stage fn)"
            % (n_given, axis_name, n_stages))
    M = n_microbatch or n_stages
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(
            "n_microbatch %d must divide the batch %d" % (M, B))
    mb = B // M
    micro = x.reshape((M, mb) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis_name, *([None] * (l.ndim - 1))), stage_params)

    def local_fn(params, micro_local):
        # params leaves: (1, ...) — this device's stage
        params = jax.tree_util.tree_map(lambda l: l[0], params)
        idx = lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state = jnp.zeros((mb,) + x.shape[1:], x.dtype)   # held activation
        out = jnp.zeros_like(micro_local)

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests microbatch t (all devices compute the slice;
            # only device 0 uses it)
            feed = lax.dynamic_index_in_dim(
                micro_local, jnp.minimum(t, M - 1), axis=0, keepdims=False)
            x_in = jnp.where(idx == 0, feed, state)
            y = fn(params, x_in)
            # last stage finishes microbatch t - (S-1) at this tick
            done_idx = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (done_idx >= 0)
            out = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(done_idx, 0), axis=0),
                lambda o: o, out)
            state = lax.ppermute(y, axis_name, fwd_perm)
            return (state, out), None

        (state, out), _ = lax.scan(tick, (state, out),
                                   jnp.arange(M + n_stages - 1))
        # replicate the last stage's collected outputs to every device
        out = lax.psum(jnp.where(idx == n_stages - 1, out,
                                 jnp.zeros_like(out)), axis_name)
        return out

    fn_sharded = shard_map(
        local_fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(), check_vma=False)
    out = fn_sharded(stage_params, micro)
    return out.reshape((B,) + x.shape[1:])
