"""ZeRO-3 machinery: layer-grouped parameter gathers for the fused step.

``grad_sync='zero3'`` goes the rest of the way from 'zero' (sharded
master params + optimizer state, one gather block at step start): the
step all-gathers each PARAMETER GROUP on demand inside the jitted
program, the backward RE-GATHERS instead of keeping the replicated
copies alive between the passes, and gradients leave the backward as
reduce-scatter.  Nothing replicated persists between steps — per-device
parameter residency is ~1/world (``bench.py zero3`` proves it).

Two tiers, the kernels-package discipline (Pallas/lax):

- **manual** (pure-dp mesh, shard_map available): the whole step body
  runs under ``shard_map`` over the dp axis.  Gathers are explicit
  ``lax.all_gather`` calls — several same-group shards flatten into ONE
  bucketed collective — and their autodiff transpose IS
  ``psum_scatter``, so the gradient reduce-scatter is guaranteed by
  construction on every backend (XLA CPU never synthesizes
  reduce-scatter from GSPMD partial sums; proven by
  tests/test_analysis.py's schedule-rule tests).
- **gspmd** (multi-axis meshes — dp×tp/ep/pp composition): grouped
  ``with_sharding_constraint`` re-shardings under the same remat
  policy; GSPMD inserts the collectives.  XLA's ReduceScatterCreator
  rewrites the gradient all-reduce+slice into reduce-scatter on
  TPU/GPU pipelines; CPU keeps the all-reduce form, which the schedule
  lint reports as a documented tier note rather than a violation.

Group boundaries are keyed by the executor plan's TOPOLOGICAL order
(executor._node_plan): each parameter belongs to the plan position of
its first consuming node.  Under the MXTPU_ZERO3_GATHER_GROUP=auto
default the PLANNER (parallel/planner.py) merges consecutive consumer
nodes ("layers") toward a target bucket size; a numeric value is the
manual N-layers-per-group override (plan_gather_groups below).
Separate per-group gathers — not one monolithic gather — are what
XLA's latency-hiding scheduler can pipeline against early forward
compute.

The backward re-gather is expressed with ``jax.checkpoint`` +
``checkpoint_name``: every gathered (replicated) value is tagged
``zero3_gather`` and the step's loss closure runs under the
``save_anything_except_these_names`` policy, so activations checkpoint
as usual while gathered parameters are dropped after the forward and
re-gathered (recomputed from the shards) inside the backward.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError, register_env

__all__ = ["ENV_ZERO3_GATHER_GROUP", "GATHER_TAG", "first_consumer_order",
           "plan_gather_groups", "remat_policy", "make_manual_gather",
           "make_gspmd_gather"]

#: checkpoint_name tag on every gathered (replicated) parameter value;
#: the step's remat policy drops exactly these between forward and
#: backward so the backward re-gathers from the shards
GATHER_TAG = "zero3_gather"

ENV_ZERO3_GATHER_GROUP = register_env(
    "MXTPU_ZERO3_GATHER_GROUP", default="auto",
    doc="grad_sync='zero3': gather grouping.  'auto' (default) derives "
        "the groups from the executor plan's first-consumer order, "
        "merged toward MXTPU_PLAN_GATHER_BUCKET bytes per collective "
        "(parallel/planner.py).  A numeric value is the manual "
        "override — N consecutive plan-order layers per group (1 = "
        "per-layer gathers; larger values fuse more parameters into "
        "fewer, bigger collectives — less dispatch overhead, less "
        "overlap) — and warns when it loses to the planned grouping "
        "on the memory model")


def first_consumer_order(symbol, param_names):
    """``{param_name: topological position of its first consumer}``.

    Positions come from the executor plan (executor._node_plan slot 5):
    a pure function of the graph, identical across processes — the same
    property the RNG fold constants rely on, so group boundaries are
    reproducible anywhere the program is.  Params never consumed by an
    op (possible in hand-built graphs) sort last, after every real
    consumer.
    """
    from ..executor import _node_plan
    wanted = set(param_names)
    order = {}
    last = 0
    for entry in _node_plan(symbol):
        node, ix = entry[0], entry[4]
        if node.is_variable:
            continue
        last = max(last, ix)
        for src, _ in node.inputs:
            if src.is_variable and src.name in wanted \
                    and src.name not in order:
                order[src.name] = ix
    for name in param_names:
        order.setdefault(name, last + 1)
    return order


def plan_gather_groups(symbol, param_names, group_layers=1):
    """Chunk ``param_names`` into gather groups of ``group_layers``
    consecutive consuming nodes each, ordered by the plan's topological
    order.  Returns a list of name-lists; every input name appears in
    exactly one group."""
    group_layers = max(1, int(group_layers))
    order = first_consumer_order(symbol, param_names)
    by_node = {}
    for name in param_names:
        by_node.setdefault(order[name], []).append(name)
    groups, current, nlayers = [], [], 0
    for ix in sorted(by_node):
        current.extend(sorted(by_node[ix]))
        nlayers += 1
        if nlayers >= group_layers:
            groups.append(current)
            current, nlayers = [], 0
    if current:
        groups.append(current)
    return groups


def remat_policy():
    """The zero3 checkpoint policy: save every residual EXCEPT gathered
    parameters (tag ``GATHER_TAG``) — activations behave as in a plain
    step, replicated parameters are re-gathered in the backward."""
    import jax
    return jax.checkpoint_policies.save_anything_except_these_names(
        GATHER_TAG)


def make_manual_gather(groups, shard_dim, shapes, world, axis_name):
    """Build ``gather(shards) -> {name: full}`` for the manual tier.

    Per group, every dim-0-sharded member flattens into ONE bucketed
    ``all_gather`` (the ZeRO gather bucket: one collective per layer
    group; its autodiff transpose is ONE ``psum_scatter`` carrying the
    whole group's gradients).  Members sharded on another dimension
    gather individually (their flattened shards would interleave
    wrongly in a dim-0 bucket).  Every replicated full value is tagged
    ``GATHER_TAG`` so the remat policy re-gathers it in the backward.

    ``shard_dim``: {name: int} — which dimension the dp axis shards.
    ``shapes``: {name: full shape}.  ``world``: dp axis size.
    """
    import jax
    from jax.ad_checkpoint import checkpoint_name

    def _tag(v):
        return checkpoint_name(v, GATHER_TAG)

    def gather(p):
        full = {}
        for g in groups:
            bucket = [n for n in g if shard_dim[n] == 0]
            singles = [n for n in g if shard_dim[n] != 0]
            if len(bucket) < 2:
                singles = bucket + singles
                bucket = []
            if bucket:
                flat = jax.numpy.concatenate(
                    [p[n].reshape(-1) for n in bucket])
                gathered = _tag(jax.lax.all_gather(
                    flat, axis_name, axis=0, tiled=True))
                # [world * bucket_elems] -> (world, bucket_elems); each
                # param's full value is its column strip re-stacked over
                # the world rows (dim-0 shards are contiguous row blocks)
                mat = gathered.reshape(world, -1)
                off = 0
                for n in bucket:
                    size = int(np.prod(shapes[n])) // world
                    strip = mat[:, off:off + size]
                    full[n] = _tag(strip.reshape(shapes[n]))
                    off += size
            for n in singles:
                full[n] = _tag(jax.lax.all_gather(
                    p[n], axis_name, axis=shard_dim[n], tiled=True))
        return full

    return gather


def make_gspmd_gather(groups, sharding_of, replicated):
    """Build ``gather(params) -> {name: full}`` for the gspmd tier:
    per-group ``with_sharding_constraint`` pairs (pin to the shard so
    the partitioner cannot hoist the gather above the compute-dtype
    cast, then demand replicated), tagged for the backward re-gather.
    GSPMD turns each replication demand into an all-gather; grouping
    here is emission ORDER (the latency-hiding scheduler keys on the
    dependency structure, one gather per parameter group member)."""
    import jax
    from jax.ad_checkpoint import checkpoint_name

    def gather(p):
        full = {}
        for g in groups:
            for n in g:
                v = jax.lax.with_sharding_constraint(p[n], sharding_of(n))
                full[n] = checkpoint_name(
                    jax.lax.with_sharding_constraint(v, replicated),
                    GATHER_TAG)
        return full

    return gather
