"""JAX version-compatibility shims for the parallel layer.

``shard_map`` has lived at three spellings across JAX releases:
``jax.experimental.shard_map.shard_map`` (<= 0.4.x, kwarg ``check_rep``),
``jax.shard_map`` (newer releases, kwarg ``check_vma``), and briefly
``jax.experimental.shard_map`` re-exporting the new one.  The parallel
modules (ring_attention, pipeline) call :func:`shard_map` from HERE with
the modern signature; this module resolves whichever spelling the
installed JAX provides and translates the kwargs — one shim, every
caller un-broken on old and new JAX alike.

When no spelling exists (a future removal, a stripped build) callers
raise a clear :class:`~mxnet_tpu.base.MXNetError` at use time and tests
skip via :data:`HAS_SHARD_MAP`.
"""
from __future__ import annotations

import inspect

from ..base import MXNetError

__all__ = ["shard_map", "HAS_SHARD_MAP"]


def _resolve():
    """(callable, kwarg-name-for-replication-check) or (None, None)."""
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        try:
            from jax.experimental.shard_map import shard_map as fn
        except ImportError:
            return None, None
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # pragma: no cover — exotic wrapper
        params = {}
    for name in ("check_vma", "check_rep"):
        if name in params:
            return fn, name
    return fn, None


_SHARD_MAP, _CHECK_KW = _resolve()

#: True when the installed JAX provides shard_map under either spelling —
#: tests gate on this instead of import-crashing
HAS_SHARD_MAP = _SHARD_MAP is not None


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """Version-tolerant ``shard_map`` (modern calling convention).

    ``check_vma=False`` maps onto the installed spelling's replication-
    check kwarg (``check_rep`` on older JAX) — the parallel kernels here
    use collectives (ppermute rings) whose replication the checker cannot
    prove, so they all pass False.
    """
    if _SHARD_MAP is None:
        raise MXNetError(
            "this JAX provides neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map — ring attention and "
            "pipeline parallelism need one of them")
    kwargs = {}
    if _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
