"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

New capability beyond the reference (which only had bucketing for long
sequences, SURVEY §5.7).  Q/K/V are sharded along the sequence axis across
the 'sp' devices; each device holds one query block and streams the K/V
blocks around the ring with ``lax.ppermute`` (neighbor exchange over ICI),
accumulating attention with the numerically-stable streaming-softmax
(flash-attention style log-sum-exp rescaling).  Compute on each hop is a
full block matmul (MXU-sized); communication overlaps with compute across
hops.

Reference pattern: Ring Attention (Liu et al. 2023) / blockwise attention —
see PAPERS.md.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "full_attention", "ring_attention_sharded"]


def full_attention(q, k, v, causal=False, scale=None):
    """Single-device attention: q,k,v (B, T, H, D) -> (B, T, H, D).

    Long sequences route through the tiled online-softmax kernel
    (mxnet_tpu/kernels/flash_attention.py — Pallas on TPU, lax scan
    elsewhere) when ``MXTPU_FUSED_KERNELS`` enables it: the (Tq x Tk)
    score matrix then never materializes.  Short sequences (at most one
    key block) and ``MXTPU_FUSED_KERNELS=0`` use the exact-softmax
    reference below."""
    B, Tq, H, D = q.shape
    scale = scale or (1.0 / np.sqrt(D))
    Tk = k.shape[1]
    from ..kernels import fused_enabled
    if fused_enabled("flash_attention"):
        from ..kernels import flash_attention as fa
        if Tk > fa.default_block():
            return fa.flash_attention(q, k, v, causal=causal, scale=scale)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), Tk - Tq)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _ring_body(axis_name, n_blocks, causal, scale, q, k0, v0, my_idx):
    """Streaming accumulation over ring hops inside shard_map."""
    B, Tq, H, D = q.shape
    Tk = k0.shape[1]

    acc = jnp.zeros((B, Tq, H, D), dtype=jnp.float32)
    m_run = jnp.full((B, H, Tq), -jnp.inf)
    s_run = jnp.zeros((B, H, Tq))

    # each hop is ONE streaming-softmax accumulation step — the same
    # online_update the flash-attention kernel runs per key block
    # (mxnet_tpu/kernels/flash_attention.py), so ring attention IS the
    # flash accumulation composed across devices and the two paths
    # cannot drift numerically
    from ..kernels.flash_attention import online_update

    def hop(carry, hop_idx):
        acc, m_run, s_run, k, v = carry
        # block owner of the K/V currently held: after h hops of the
        # i -> i+1 ring, device i holds block (i - h) mod n
        kv_idx = (my_idx - hop_idx) % n_blocks
        if causal:
            q_pos = my_idx * Tq + jnp.arange(Tq)
            k_pos = kv_idx * Tk + jnp.arange(Tk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((Tq, Tk), dtype=bool)
        acc, m_run, s_run = online_update(
            acc, m_run, s_run, q, k, v, scale, mask[None, None])
        # pass K/V to the next device on the ring (ICI neighbor exchange)
        perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (acc, m_run, s_run, k, v), None

    (acc, m_run, s_run, _, _), _ = lax.scan(
        hop, (acc, m_run, s_run, k0, v0), jnp.arange(n_blocks))
    s_run = jnp.maximum(s_run, 1e-20)
    return (acc / s_run.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           scale=None):
    """Ring attention with q/k/v sharded on the sequence axis (axis 1) over
    ``axis_name`` of ``mesh``.  q,k,v: (B, T, H, D) global shapes."""
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    n_blocks = mesh.shape[axis_name]
    D = q.shape[-1]
    scale = scale or (1.0 / np.sqrt(D))
    spec = P(None, axis_name, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        my_idx = lax.axis_index(axis_name)
        return _ring_body(axis_name, n_blocks, causal, scale, q_blk, k_blk,
                          v_blk, my_idx)

    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=False,
                   scale=None):
    """Entry point: ring attention when a mesh with ``axis_name`` is given,
    plain (still flash-style-stable) attention otherwise."""
    if mesh is not None and axis_name in mesh.shape and \
            mesh.shape[axis_name] > 1:
        return ring_attention_sharded(q, k, v, mesh, axis_name, causal, scale)
    return full_attention(q, k, v, causal=causal, scale=scale)
