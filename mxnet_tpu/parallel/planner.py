"""mxplan — the automatic sharding planner + elastic-resume artifact.

ZeRO-3 (zero3.py) made fully-sharded training WORK; this module makes it
CHOSEN.  Every run used to hand-pick its mesh, its param rules and the
``MXTPU_ZERO3_GATHER_GROUP`` knob, and a checkpoint was welded to the
world size that wrote it.  The planner closes both gaps with ONE
artifact, the :class:`ShardingPlan`:

- **prescriptive** (:func:`plan`): given a symbol graph, the device
  inventory and an HBM budget, choose the mesh shape, the gradient-sync
  strategy (replicate / dp-shard / zero3 — the cheapest-comm strategy
  whose modeled per-device bytes fit the budget) and the per-param
  sharding actions.  ``SPMDTrainer(plan=...)`` / ``SPMDModule(plan=...)``
  consume it instead of ad-hoc arguments.
- **derived gather groups** (:func:`derive_gather_groups`): zero3 gather
  groups come from the executor plan's first-consumer order, merged
  toward a target bucket size (``MXTPU_PLAN_GATHER_BUCKET``) — this is
  the ``MXTPU_ZERO3_GATHER_GROUP=auto`` default; a numeric override
  still wins but warns when it loses to the planned grouping on the
  memory model (:func:`group_cost`).
- **descriptive** (:meth:`ShardingPlan.from_trainer`): every bound
  trainer records the plan it actually executes;
  ``SPMDTrainer.save_checkpoint`` persists it in the checkpoint
  manifest, so a resume — on ANY world size — knows exactly what wrote
  the bytes.  :func:`check_inventory` is the pre-resume gate
  (``tools/plan_explain.py --check``, ``tools/ckpt_fsck.py --devices``):
  world-size changes are a NOTE (gather-on-save checkpoints re-shard
  elastically through ``set_params``), unsatisfiable mesh axes, a
  batch the new dp axis cannot shard, or a blown HBM budget are
  PROBLEMS.
- **explainable**: :meth:`ShardingPlan.explain` renders every decision
  with the byte model behind it — "annotate the graph, let the planner
  pick" is only trustworthy when the pick can be audited.

Everything here except :func:`plan`'s symbol-shape inference and
:meth:`from_trainer` is jax-free pure-dict math, so the CLI gates run on
hosts with no accelerator runtime (the mxlint/ckpt_fsck idiom).
"""
from __future__ import annotations

import hashlib
import json
import re

from ..base import MXNetError, get_env, register_env

__all__ = ["PLAN_VERSION", "ShardingPlan", "plan", "derive_gather_groups",
           "group_cost", "check_inventory", "diff_param_sets",
           "ENV_PLAN_GATHER_BUCKET", "ENV_PLAN_HBM_BUDGET"]

#: manifest/file schema version of a serialized ShardingPlan
PLAN_VERSION = 1

ENV_PLAN_GATHER_BUCKET = register_env(
    "MXTPU_PLAN_GATHER_BUCKET", default=str(4 << 20),
    doc="mxplan: target bytes per zero3 gather group under "
        "MXTPU_ZERO3_GATHER_GROUP=auto — consecutive plan-order layers "
        "merge into one bucketed collective until the group's gathered "
        "bytes would exceed this (bigger = fewer dispatches, less "
        "gather/compute overlap and a higher replicated peak)")

ENV_PLAN_HBM_BUDGET = register_env(
    "MXTPU_PLAN_HBM_BUDGET", default="0",
    doc="mxplan: per-device HBM budget in bytes for planner.plan()'s "
        "strategy choice when the caller passes none (0 = unconstrained "
        "— the planner keeps params replicated and says so in the plan's "
        "decisions)")

#: optimizer kind -> in-graph state slots per parameter (mirrors
#: SPMDTrainer._init_opt_state; the byte model prices opt state with it
#: — use :func:`_opt_slots_of`, which also handles momentum-less sgd
#: allocating ZERO slots)
_OPT_SLOTS = {"sgd": 1, "ccsgd": 1, "adam": 2, "rmsprop": 1}


def _opt_slots_of(kind, momentum=None):
    """State slots per parameter, exactly as _init_opt_state allocates
    them: sgd/ccsgd carry a slot only when momentum is engaged."""
    slots = _OPT_SLOTS.get(kind, 0)
    if kind in ("sgd", "ccsgd") and not momentum:
        slots = 0
    return slots

_DTYPE_BYTES = {"float64": 8, "float32": 4, "bfloat16": 2, "float16": 2,
                "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1,
                "bool": 1}


def _nelem(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _itemsize(dtype):
    return _DTYPE_BYTES.get(str(dtype), 4)


def _pbytes(rec):
    """Full-size bytes of one plan param record."""
    return _nelem(rec["shape"]) * _itemsize(rec.get("dtype", "float32"))


# ---------------------------------------------------------------------------
# gather-group derivation + the memory model (the =auto default)
# ---------------------------------------------------------------------------

def group_cost(groups, sizes):
    """The memory model one grouping is judged by: ``(collectives,
    peak_bytes)`` — the manual tier issues ONE bucketed collective per
    group, and one group's gathered (replicated) bytes is the step's
    transient parameter peak.  Fewer collectives cost less dispatch;
    a smaller peak costs less HBM.  A grouping that is worse on BOTH
    axes is Pareto-dominated (``_plan_zero3`` warns when a manual
    ``MXTPU_ZERO3_GATHER_GROUP`` value loses to the planned grouping
    this way)."""
    if not groups:
        return (0, 0)
    peak = max(sum(int(sizes.get(n, 0)) for n in g) for g in groups)
    return (len(groups), peak)


def dominates(a, b):
    """True when cost ``a`` Pareto-dominates ``b``: no worse on both
    axes, strictly better on at least one."""
    return a[0] <= b[0] and a[1] <= b[1] and a != b


def resolve_bucket(bucket_bytes=None):
    """The effective gather-bucket target: the explicit value, or
    ``MXTPU_PLAN_GATHER_BUCKET`` (garbage degrades to the default)."""
    if bucket_bytes is None:
        try:
            bucket_bytes = int(
                get_env(ENV_PLAN_GATHER_BUCKET, str(4 << 20)) or (4 << 20))
        except (TypeError, ValueError):
            bucket_bytes = 4 << 20
    return max(1, int(bucket_bytes))


def derive_gather_groups(symbol, param_names, shapes, itemsize=4,
                         bucket_bytes=None):
    """The planner's gather grouping (``MXTPU_ZERO3_GATHER_GROUP=auto``).

    Layer-granularity groups come from the executor plan's
    first-consumer order (zero3.plan_gather_groups at group size 1 — a
    pure function of the graph, identical across processes), then
    consecutive layers greedy-merge while the merged group's gathered
    bytes stay within ``bucket_bytes`` (default
    ``MXTPU_PLAN_GATHER_BUCKET``).  Small layers (biases, norms) fuse
    into their neighbors' collectives; a layer bigger than the bucket
    keeps its own group — the bucket bounds merging, not splitting.

    ``itemsize``: bytes per element on the wire (the comm dtype —
    compute_dtype for floating params under mixed precision).
    """
    from . import zero3 as z3
    if not param_names:
        return []
    bucket_bytes = resolve_bucket(bucket_bytes)
    layers = z3.plan_gather_groups(symbol, param_names, 1)
    sizes = {n: _nelem(shapes[n]) * int(itemsize) for n in param_names}
    groups, cur, cur_bytes = [], [], 0
    for layer in layers:
        lb = sum(sizes[n] for n in layer)
        if cur and cur_bytes + lb > bucket_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.extend(layer)
        cur_bytes += lb
    if cur:
        groups.append(cur)
    return groups


# ---------------------------------------------------------------------------
# the byte model (what "fits" means)
# ---------------------------------------------------------------------------

def _strategy_bytes(param_bytes, opt_bytes, comm_bytes, max_group_bytes,
                    world):
    """Modeled steady-state per-device parameter-side bytes of each
    strategy (activations ride on top of all three equally, so they
    cancel out of the comparison):

    - ``allreduce``: replicated f32 master + opt state + one comm-dtype
      gradient set.
    - ``zero``: 1/world shards of master+opt, but the step's gather
      block replicates ALL params in comm dtype at once (plus the
      gradients before their reduce-scatter).
    - ``zero3``: 1/world shards; only ONE gather group is replicated at
      a time (backward re-gather), and gradients reduce-scatter as they
      are produced — the transient is ~2 groups (one live, one in
      flight under the latency-hiding scheduler).
    """
    w = max(1, int(world))
    return {
        "allreduce": param_bytes + opt_bytes + comm_bytes,
        "zero": (param_bytes + opt_bytes) // w + 2 * comm_bytes,
        "zero3": (param_bytes + opt_bytes) // w + 2 * max_group_bytes,
    }


# ---------------------------------------------------------------------------
# the artifact
# ---------------------------------------------------------------------------

class ShardingPlan(object):
    """One serializable, explainable sharding decision.

    Wraps the plain-JSON ``doc`` (the form persisted in checkpoint
    manifests and plan files); every accessor is a dict read, so a plan
    loaded on a jax-free host behaves identically to one built from a
    live trainer."""

    def __init__(self, doc):
        if not isinstance(doc, dict):
            raise MXNetError("ShardingPlan: doc must be a dict, got %r"
                             % type(doc).__name__)
        version = int(doc.get("version", 0))
        if version != PLAN_VERSION:
            raise MXNetError(
                "ShardingPlan: unsupported plan version %r (this build "
                "understands %d) — re-plan on the writing side or "
                "upgrade this one" % (doc.get("version"), PLAN_VERSION))
        self.doc = doc

    # -- construction -------------------------------------------------------
    @classmethod
    def from_doc(cls, doc):
        return cls(doc)

    @classmethod
    def load(cls, path):
        with open(path) as f:
            return cls(json.load(f))

    @classmethod
    def from_trainer(cls, trainer):
        """The DESCRIPTIVE plan: what a bound SPMDTrainer actually
        executes — world, mesh axes, per-param resolved placement,
        zero3 gather groups.  ``save_checkpoint`` persists this doc in
        the manifest so a resume on a different inventory knows the
        writing run's layout."""
        mesh = trainer.mesh
        mesh_axes = {str(k): int(v) for k, v in mesh.shape.items()} \
            if mesh is not None else {}
        world = mesh_axes.get(trainer.data_axis, 1)
        params = {}
        for name in trainer.param_names:
            shape = tuple(int(d) for d in trainer.arg_shapes[name])
            spec = trainer._param_spec(name, shape)
            entries = tuple(spec)
            dims = [i for i, e in enumerate(entries)
                    if e == trainer.data_axis]
            dim = dims[0] if len(dims) == 1 and all(
                e in (None, trainer.data_axis) for e in entries) else None
            dtype = "float32"
            if trainer.params and name in trainer.params:
                dtype = str(trainer.params[name].dtype)
            params[name] = {
                "shape": list(shape), "dtype": dtype,
                "spec": [None if e is None else str(e) for e in entries],
                "action": ("shard" if any(entries) else "replicate"),
                "dim": dim,
            }
        kind = type(trainer.optimizer).__name__.lower()
        comm_itemsize = trainer.compute_dtype.itemsize \
            if trainer.compute_dtype is not None else 4
        bucket = resolve_bucket()
        doc = {
            "version": PLAN_VERSION,
            "source": "trainer",
            "world": int(world),
            "mesh_axes": mesh_axes,
            "data_axis": trainer.data_axis,
            "batch_size": int(trainer.batch_size),
            "grad_sync": trainer.grad_sync,
            "zero3_tier": trainer.zero3_tier,
            "compute_dtype": (str(trainer.compute_dtype)
                              if trainer.compute_dtype is not None
                              else None),
            "optimizer": kind,
            "opt_slots": _opt_slots_of(
                kind, getattr(trainer.optimizer, "momentum", None)),
            "comm_itemsize": int(comm_itemsize),
            "gather_bucket": bucket,
            "hbm_budget": 0,
            "param_shardings": {
                str(k): [None if e is None else str(e) for e in
                         (tuple(v) if not isinstance(v, str) else (v,))]
                for k, v in (trainer.param_shardings or {}).items()},
            "params": params,
            "gather_groups": [list(g) for g in trainer._zero3_groups],
            "decisions": ["recorded from a bound trainer (grad_sync=%r, "
                          "mesh=%s)" % (trainer.grad_sync,
                                        mesh_axes or "none")],
        }
        if trainer._zero and trainer.param_shardings:
            # explicit rules survive zero/zero3: the step does NOT widen
            # these params to replicated (the silent-widening fix) —
            # record each kept spec so plan_explain shows the decision
            from .trainer import _spec_for
            for name in trainer.param_names:
                spec = _spec_for(name, trainer.arg_shapes[name],
                                 trainer.param_shardings)
                if tuple(spec):
                    doc["decisions"].append(
                        "%s: explicit shard spec %s kept under "
                        "grad_sync=%r (not widened to replicated)"
                        % (name,
                           [None if e is None else str(e)
                            for e in tuple(spec)],
                           trainer.grad_sync))
        p = cls(doc)
        doc["bytes"] = p._byte_model()
        return p

    # -- accessors ----------------------------------------------------------
    @property
    def world(self):
        return int(self.doc.get("world", 1))

    @property
    def mesh_axes(self):
        return dict(self.doc.get("mesh_axes") or {})

    @property
    def data_axis(self):
        return self.doc.get("data_axis", "dp")

    @property
    def grad_sync(self):
        return self.doc.get("grad_sync", "allreduce")

    @property
    def batch_size(self):
        return int(self.doc.get("batch_size", 0))

    @property
    def params(self):
        return dict(self.doc.get("params") or {})

    @property
    def gather_groups(self):
        return [list(g) for g in (self.doc.get("gather_groups") or [])]

    @property
    def param_shardings(self):
        """The POLICY rules (regex -> axes tuple) a consuming trainer
        re-applies; derived per-param specs stay descriptive."""
        return {k: tuple(v) for k, v in
                (self.doc.get("param_shardings") or {}).items()}

    @property
    def compute_dtype(self):
        return self.doc.get("compute_dtype")

    @property
    def decisions(self):
        return list(self.doc.get("decisions") or [])

    # -- serialization ------------------------------------------------------
    def to_doc(self):
        return json.loads(self.to_json())

    def to_json(self):
        return json.dumps(self.doc, indent=2, sort_keys=True)

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    def digest(self):
        """Stable content digest (sha256 of the canonical JSON) — two
        plans with the same decisions have the same digest regardless
        of which process serialized them."""
        return hashlib.sha256(
            json.dumps(self.doc, sort_keys=True,
                       separators=(",", ":")).encode()).hexdigest()

    # -- the byte model -----------------------------------------------------
    def _byte_model(self, world=None):
        params = self.params
        pb = sum(_pbytes(r) for r in params.values())
        comm_itemsize = int(self.doc.get("comm_itemsize", 4))
        cb = sum(_nelem(r["shape"]) * comm_itemsize
                 for r in params.values())
        ob = int(self.doc.get("opt_slots", 0)) * pb
        sizes = {n: _nelem(r["shape"]) * comm_itemsize
                 for n, r in params.items()}
        groups = self.gather_groups
        _, peak = group_cost(groups, sizes)
        if not groups and params:
            # no recorded groups (non-zero3 plan): model zero3 at layer
            # granularity as the largest single param
            peak = max(sizes.values())
        w = self.world if world is None else int(world)
        return {
            "param": pb, "opt": ob, "comm": cb,
            "max_group": peak,
            "per_device": _strategy_bytes(pb, ob, cb, peak, w),
        }

    # -- gates --------------------------------------------------------------
    def check_inventory(self, ndevices, hbm_bytes=None):
        """Does this plan still fit ``ndevices`` (and optionally a
        per-device ``hbm_bytes`` budget)?  Returns ``(problems,
        notes)``: problems are hard misfits a resume must not walk onto
        (unsatisfiable mesh axes, a batch the dp axis cannot shard, a
        blown byte budget); notes are survivable differences the
        operator should know about (a world-size change — gather-on-save
        checkpoints re-shard elastically through ``set_params``)."""
        problems, notes = [], []
        ndevices = int(ndevices)
        if ndevices <= 0:
            return (["device inventory is empty (%d devices)"
                     % ndevices], notes)
        other = 1
        for axis, size in self.mesh_axes.items():
            if axis != self.data_axis:
                other *= int(size)
        if other > 1 and ndevices % other:
            problems.append(
                "mesh axes %s need a multiple of %d devices; inventory "
                "has %d" % (self.mesh_axes, other, ndevices))
            return (problems, notes)
        dp = max(1, ndevices // other)
        if self.batch_size and self.batch_size % dp:
            # EVERY strategy dp-shards the batch over the mesh (the
            # placement layer rejects an indivisible one), and the
            # zero3 manual tier additionally shard_maps the step
            problems.append(
                "batch %d does not divide the %d-way dp axis a resume "
                "would build on %d devices — pad the batch (iterator "
                "default) or change it"
                % (self.batch_size, dp, ndevices))
        budget = hbm_bytes
        if budget is None:
            budget = int(self.doc.get("hbm_budget", 0) or 0)
        if budget:
            model = self._byte_model(world=dp)
            need = model["per_device"].get(self.grad_sync, 0)
            if need > budget:
                problems.append(
                    "modeled per-device bytes at world=%d under %r "
                    "(%d) exceed the HBM budget (%d) — re-plan on this "
                    "inventory" % (dp, self.grad_sync, need, budget))
        if dp != self.world:
            notes.append(
                "elastic re-shard required: plan was written at "
                "world=%d, inventory gives dp=%d — gather-on-save "
                "checkpoints restore through set_params re-sharding "
                "(docs/how_to/planner.md)" % (self.world, dp))
        return (problems, notes)

    # -- explanation --------------------------------------------------------
    def explain(self):
        """Human-readable walkthrough of the plan (the
        ``tools/plan_explain.py`` body)."""
        d = self.doc
        model = d.get("bytes") or self._byte_model()
        lines = []
        lines.append("ShardingPlan v%d (%s)" % (PLAN_VERSION,
                                                d.get("source", "?")))
        lines.append("  mesh: %s  (world=%d over axis %r, batch %d)"
                     % (self.mesh_axes or "single device", self.world,
                        self.data_axis, self.batch_size))
        lines.append("  strategy: grad_sync=%r%s  compute_dtype=%s"
                     % (self.grad_sync,
                        (" tier=%s" % d["zero3_tier"])
                        if d.get("zero3_tier") else "",
                        d.get("compute_dtype") or "float32"))
        params = self.params
        sharded = sorted(n for n, r in params.items()
                         if r.get("action") == "shard")
        repl = sorted(set(params) - set(sharded))
        pb, ob = model.get("param", 0), model.get("opt", 0)
        lines.append("  params: %d total, %d bytes master + %d bytes "
                     "optimizer state" % (len(params), pb, ob))
        lines.append("    sharded (%d): %s" % (len(sharded),
                                               ", ".join(sharded) or "-"))
        lines.append("    replicated (%d): %s" % (len(repl),
                                                  ", ".join(repl) or "-"))
        per = model.get("per_device", {})
        for strat in ("allreduce", "zero", "zero3"):
            mark = " <= chosen" if strat == self.grad_sync else ""
            lines.append("  modeled bytes/device [%s]: %d%s"
                         % (strat, per.get(strat, 0), mark))
        groups = self.gather_groups
        if groups:
            comm_itemsize = int(d.get("comm_itemsize", 4))
            lines.append("  gather groups (%d, first-consumer order, "
                         "bucket target %s bytes):"
                         % (len(groups),
                            d.get("gather_bucket", "default")))
            for i, g in enumerate(groups):
                gb = sum(_nelem(params[n]["shape"]) * comm_itemsize
                         for n in g if n in params)
                lines.append("    [%d] %s (%d bytes)"
                             % (i, ", ".join(g), gb))
        for dec in self.decisions:
            lines.append("  decision: %s" % dec)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# prescriptive planning
# ---------------------------------------------------------------------------

def plan(symbol, data_shapes, label_shapes=None, world=None, devices=None,
         hbm_budget=None, optimizer="sgd", optimizer_params=None,
         compute_dtype=None, param_shardings=None, grad_sync=None,
         gather_bucket=None):
    """Choose a sharding plan for ``symbol`` on the given inventory.

    ``world``/``devices``: the device inventory (one of them; with
    neither, ``jax.devices()`` is consulted — the only jax touch in
    this module).  ``hbm_budget``: per-device byte budget (default
    ``MXTPU_PLAN_HBM_BUDGET``; 0 = unconstrained).  ``grad_sync``
    pins the strategy and the planner only derives mesh/rules/groups.

    The strategy choice walks allreduce -> zero -> zero3 (cheapest
    communication first) and takes the first whose modeled per-device
    bytes (:func:`_strategy_bytes`) fit the budget; when nothing fits,
    it raises with the numbers — an impossible plan must fail at
    planning time, not as an OOM three hours into the run.
    """
    decisions = []
    if world is None:
        if devices is not None:
            world = len(devices)
        else:
            import jax
            devices = jax.devices()
            world = len(devices)
            decisions.append("inventory from jax.devices(): %d" % world)
    world = int(world)
    if world <= 0:
        raise MXNetError("planner.plan: empty device inventory")
    if hbm_budget is None:
        try:
            hbm_budget = int(get_env(ENV_PLAN_HBM_BUDGET, "0") or 0)
        except (TypeError, ValueError):
            hbm_budget = 0
        if hbm_budget:
            decisions.append("HBM budget from MXTPU_PLAN_HBM_BUDGET: %d"
                             % hbm_budget)
    if not hbm_budget and devices is not None:
        # best effort: a real accelerator device advertises its HBM
        for dev in devices[:1]:
            try:
                stats = dev.memory_stats()
                hbm_budget = int(stats.get("bytes_limit", 0) or 0)
                if hbm_budget:
                    decisions.append(
                        "HBM budget from device memory_stats: %d"
                        % hbm_budget)
            except Exception:  # noqa: BLE001 — CPU devices have none
                pass
    hbm_budget = int(hbm_budget or 0)

    # shapes come from the graph, exactly as bind() infers them
    from ..io import DataDesc
    data_shapes = [d if isinstance(d, DataDesc) else DataDesc(d[0], d[1])
                   for d in data_shapes]
    label_shapes = [l if isinstance(l, DataDesc) else DataDesc(l[0], l[1])
                    for l in (label_shapes or [])]
    shapes = {d.name: d.shape for d in data_shapes + label_shapes}
    arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
    arg_names = symbol.list_arguments()
    input_names = set(shapes)
    param_shapes = {n: tuple(int(x) for x in s)
                    for n, s in zip(arg_names, arg_shapes)
                    if n not in input_names}
    batch_size = int(data_shapes[0].shape[0])

    comm_itemsize = _itemsize(compute_dtype) if compute_dtype else 4
    kind = str(optimizer).lower()
    opt_slots = _opt_slots_of(kind,
                              (optimizer_params or {}).get("momentum"))
    gather_bucket = resolve_bucket(gather_bucket)

    # per-param action under dp sharding (mirrors _param_spec's rule:
    # explicit regex rules win, otherwise shard the first dp-divisible
    # dimension)
    rules = dict(param_shardings or {})
    params = {}
    for name in sorted(param_shapes):
        shape = param_shapes[name]
        spec = None
        for pattern, axes in rules.items():
            if re.match(pattern, name):
                spec = [None if a is None else str(a)
                        for a in (axes if not isinstance(axes, str)
                                  else (axes,))]
                break
        rec = {"shape": list(shape), "dtype": "float32"}
        if spec is not None:
            rec["spec"] = spec
            rec["action"] = "shard" if any(spec) else "replicate"
            rec["dim"] = None
            rec["rule"] = "explicit"
        else:
            dim = None
            for i, d in enumerate(shape):
                if d % world == 0 and d >= world:
                    dim = i
                    break
            if dim is None:
                rec["spec"] = [None] * len(shape)
                rec["action"] = "replicate"
                rec["dim"] = None
                rec["rule"] = "indivisible"
            else:
                rec["spec"] = ["dp" if i == dim else None
                               for i in range(len(shape))]
                rec["action"] = "shard"
                rec["dim"] = dim
                rec["rule"] = "dp"
        params[name] = rec

    groups = derive_gather_groups(
        symbol, sorted(n for n, r in params.items()
                       if r.get("rule") == "dp"),
        {n: tuple(r["shape"]) for n, r in params.items()},
        itemsize=comm_itemsize, bucket_bytes=gather_bucket)

    doc = {
        "version": PLAN_VERSION,
        "source": "planner",
        "world": world,
        "mesh_axes": {"dp": world},
        "data_axis": "dp",
        "batch_size": batch_size,
        "grad_sync": grad_sync or "allreduce",
        "zero3_tier": None,
        "compute_dtype": str(compute_dtype) if compute_dtype else None,
        "optimizer": kind,
        "opt_slots": opt_slots,
        "comm_itemsize": comm_itemsize,
        "gather_bucket": gather_bucket,
        "hbm_budget": hbm_budget,
        "param_shardings": {
            str(k): [None if a is None else str(a) for a in
                     (tuple(v) if not isinstance(v, str) else (v,))]
            for k, v in rules.items()},
        "params": params,
        "gather_groups": [list(g) for g in groups],
        "decisions": decisions,
    }
    p = ShardingPlan(doc)
    model = p._byte_model()
    doc["bytes"] = model

    if grad_sync is not None:
        decisions.append("grad_sync pinned by caller: %r" % grad_sync)
    elif not hbm_budget:
        doc["grad_sync"] = "allreduce"
        decisions.append(
            "no HBM budget: params assumed to fit replicated "
            "(grad_sync='allreduce'); pass hbm_budget= or set "
            "MXTPU_PLAN_HBM_BUDGET to engage sharding")
    else:
        chosen = None
        for strat in ("allreduce", "zero", "zero3"):
            need = model["per_device"][strat]
            if need <= hbm_budget:
                chosen = strat
                decisions.append(
                    "%r fits: %d modeled bytes/device <= %d budget "
                    "(cheapest-communication strategy that fits)"
                    % (strat, need, hbm_budget))
                break
            decisions.append("%r does not fit: %d modeled bytes/device "
                             "> %d budget" % (strat, need, hbm_budget))
        if chosen is None:
            raise MXNetError(
                "planner.plan: no strategy fits %d bytes/device on %d "
                "devices (modeled: %s) — more devices, a bigger budget, "
                "or a smaller model" % (hbm_budget, world,
                                        model["per_device"]))
        doc["grad_sync"] = chosen
    if world > 1 and batch_size % world:
        raise MXNetError(
            "planner.plan: batch %d does not divide the %d-way dp axis "
            "the data shards over — pad the batch (iterator default) "
            "or change it" % (batch_size, world))
    return p


# ---------------------------------------------------------------------------
# jax-free module-level gates (tools/plan_explain.py, tools/ckpt_fsck.py)
# ---------------------------------------------------------------------------

def check_inventory(doc, ndevices, hbm_bytes=None):
    """``(problems, notes)`` for a plain plan doc against ``ndevices``
    — the jax-free entry the CLI gates import through the synthetic
    package stub.  An unreadable/unversioned doc is itself a problem
    (a resume must not trust bytes it cannot interpret)."""
    try:
        p = ShardingPlan(doc)
    except MXNetError as e:
        return ([str(e)], [])
    return p.check_inventory(ndevices, hbm_bytes=hbm_bytes)


def diff_param_sets(saved_params, current_names, kind="parameter"):
    """Problems list for a save->resume param-set change: a param
    ADDED to the model since the save, REMOVED from it, or RESHAPED
    must fail the resume with names — never silently misload.
    ``saved_params``: the plan doc's params dict (or any
    ``{name: {"shape": [...]}}``); ``current_names``: either a name
    iterable or a ``{name: shape}`` dict (shapes then compared too)."""
    saved = dict(saved_params or {})
    shapes = None
    if isinstance(current_names, dict):
        shapes = {n: tuple(int(d) for d in s)
                  for n, s in current_names.items()}
        current = set(shapes)
    else:
        current = set(current_names)
    problems = []
    added = sorted(current - set(saved))
    removed = sorted(set(saved) - current)
    if added:
        problems.append(
            "%s(s) %s exist in the model but not in the checkpoint "
            "(added since the save)" % (kind, ", ".join(added)))
    if removed:
        problems.append(
            "%s(s) %s exist in the checkpoint but not in the model "
            "(removed since the save)" % (kind, ", ".join(removed)))
    if shapes:
        for name in sorted(current & set(saved)):
            rec = saved[name]
            want = tuple(int(d) for d in (rec.get("shape") or ())) \
                if isinstance(rec, dict) else tuple(rec)
            if want and shapes[name] != want:
                problems.append(
                    "%s %s changed shape: checkpoint %s vs model %s"
                    % (kind, name, list(want), list(shapes[name])))
    return problems
