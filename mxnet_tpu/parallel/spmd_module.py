"""SPMDModule — Module-API adapter over SPMDTrainer.

Gives reference scripts (`mod.fit(train_iter, ...)`) the mesh-sharded fused
step: where `mx.mod.Module(ctx=[gpu(0)..gpu(7)])` runs 8 executors + a
KVStore in the reference, `SPMDModule(symbol, mesh=...)` runs ONE XLA
program over the mesh.  forward_backward+update are a single fused step
(update() is then a no-op), matching BaseModule.fit's call order.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from ..module.base_module import BaseModule
from ..ndarray import NDArray
from .trainer import SPMDTrainer
from .mesh import local_mesh

__all__ = ["SPMDModule"]


class SPMDModule(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging, mesh=None,
                 param_shardings=None, data_axis="dp", compute_dtype=None,
                 grad_sync=None, plan=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._mesh = mesh
        self._param_shardings = param_shardings
        self._data_axis = data_axis
        self._compute_dtype = compute_dtype
        # 'allreduce' | 'zero' | 'zero3' (None follows MXNET_GRAD_SYNC);
        # forwarded to the SPMDTrainer built at init_optimizer
        self._grad_sync = grad_sync
        # a planner.ShardingPlan (or its doc) supplies grad_sync /
        # sharding rules / compute dtype as one artifact instead of the
        # ad-hoc arguments above (explicit arguments still win)
        self._plan = plan
        self._trainer = None
        self._optimizer_spec = ("sgd", {})

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert not inputs_need_grad, "SPMDModule: inputs_need_grad unsupported"
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.for_training = for_training
        self.binded = True

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        assert self.binded
        self._init_args = (initializer, arg_params, aux_params)
        self.params_initialized = True

    def init_optimizer(self, kvstore="tpu", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        optimizer_params = dict(optimizer_params)
        batch = self._data_shapes[0][1][0] if not hasattr(
            self._data_shapes[0], "shape") else self._data_shapes[0].shape[0]
        optimizer_params.setdefault("rescale_grad", 1.0 / batch)
        self._trainer = SPMDTrainer(
            self._symbol, optimizer, optimizer_params,
            mesh=self._mesh if self._mesh is not None else None,
            data_axis=self._data_axis,
            param_shardings=self._param_shardings,
            compute_dtype=self._compute_dtype,
            grad_sync=self._grad_sync, plan=self._plan)
        self._trainer.bind(self._data_shapes, self._label_shapes)
        initializer, arg_params, aux_params = self._init_args
        self._trainer.init_params(initializer, arg_params, aux_params)
        self.optimizer_initialized = True

    # fused: forward_backward does the whole step; update is a no-op
    def forward_backward(self, data_batch):
        from ..io import StagedBatch
        if isinstance(data_batch, StagedBatch):
            # inputs already placed on the mesh (DevicePrefetchIter):
            # the step skips the host->device transfer
            self._trainer.step(data_batch)
            return
        arrays = list(data_batch.data) + list(data_batch.label or [])
        self._trainer.step(*arrays)

    def forward(self, data_batch, is_train=None):
        if is_train:
            return self.forward_backward(data_batch)
        from ..io import StagedBatch
        if isinstance(data_batch, StagedBatch):
            self._eval_outputs = self._trainer.eval_step(data_batch)
            return
        arrays = list(data_batch.data) + list(data_batch.label or [])
        if len(arrays) < len(self._trainer.input_names):
            # predict without labels: pad with zeros of the right shape
            import numpy as np
            for name in self._trainer.input_names[len(arrays):]:
                shape = dict((d.name, d.shape) if hasattr(d, "name") else d
                             for d in (self._label_shapes or []))[name]
                arrays.append(np.zeros(shape, dtype="float32"))
        self._eval_outputs = self._trainer.eval_step(*arrays)

    def backward(self, out_grads=None):
        pass  # folded into forward_backward

    def update(self):
        pass  # folded into forward_backward

    def get_outputs(self, merge_multi_context=True):
        if getattr(self, "_eval_outputs", None) is not None:
            outs = [NDArray._from_jax(o) for o in self._eval_outputs]
            self._eval_outputs = None
            return outs
        return self._trainer.outputs

    def _deferred_metric_trainer(self):
        return self._trainer  # None before init_optimizer

    def update_metric(self, eval_metric, labels):
        if getattr(self, "_eval_outputs", None) is None and \
                self._deferred_metric_update(eval_metric):
            # train-step path with in-graph accumulation: the step already
            # counted this batch (guard-skipped steps excluded in-graph)
            return
        if getattr(self, "_eval_outputs", None) is None and \
                self._trainer.step_guard:
            # train-step outputs: a guard-skipped step's outputs are
            # non-finite — keep them out of summing metrics
            self._trainer.flush_step_guard()
            if self._trainer.last_step_skipped:
                return
        eval_metric.update(labels, self.get_outputs())

    def get_params(self):
        return self._trainer.get_params()

    def get_optimizer_states(self):
        """Serialized optimizer state for fit(checkpoint=...) — COLLECTIVE
        under sharded params (all ranks must call together)."""
        return self._trainer.get_states()

    def set_optimizer_states(self, states):
        self._trainer.set_states(states)

    @property
    def sharding_plan(self):
        """The descriptive :class:`~mxnet_tpu.parallel.planner.
        ShardingPlan` of the bound trainer (None before
        init_optimizer) — ``.explain()`` renders the layout."""
        return None if self._trainer is None \
            else self._trainer.sharding_plan

    @property
    def skipped_update_count(self):
        """Updates skipped by the fused step's NaN/Inf guard."""
        return self._trainer.skipped_steps

    @property
    def consecutive_bad_steps(self):
        """Current run of guard-skipped updates."""
        return self._trainer.consecutive_bad_steps

    def install_monitor(self, mon):
        raise MXNetError("SPMDModule does not support Monitor taps (use "
                         "mx.mod.Module for monitored debugging)")
