"""Parallelism over device meshes — the TPU-native replacement for the
reference's multi-executor + parameter-server stack.

The reference scales by running one executor per GPU and reducing gradients
through KVStore/ps-lite (SURVEY §2.3).  Here the unit of scaling is a
``jax.sharding.Mesh`` with named axes (dp/tp/sp/pp/ep): one jit-compiled
training step is annotated with shardings and GSPMD partitions it across
the mesh, inserting AllReduce/AllGather/ReduceScatter over ICI — the
collectives the reference hand-wires through NCCL/ZMQ fall out of the
compiler.

Components:
- mesh.py: mesh construction helpers
- planner.py: mxplan — the automatic sharding planner (mesh shape,
  replicate/dp-shard/zero3 strategy under an HBM budget, derived zero3
  gather groups) and the serializable ShardingPlan artifact checkpoints
  persist for elastic world-size resume
- trainer.py: SPMDTrainer — fused fwd+bwd+optimizer-update step, sharded
  over the mesh (the kvstore='tpu' fast path and the bench path)
- spmd_module.py: SPMDModule — Module-API adapter over SPMDTrainer
- ring_attention.py: ring attention over the 'sp' axis (sequence/context
  parallelism — capability beyond the reference, SURVEY §5.7)
- pipeline.py: GPipe-style microbatch pipeline over the 'pp' axis
  (shard_map + ppermute neighbor exchange)
- moe.py: GShard-style top-2 mixture-of-experts over the 'ep' axis
  (dispatch/combine einsums -> all_to_all under GSPMD)
- compat.py: JAX version shims (the shard_map spelling/kwarg drift)
"""
from .compat import HAS_SHARD_MAP
from .mesh import build_mesh, default_mesh, local_mesh
from .trainer import SPMDTrainer
from . import zero3  # noqa: F401 — EAGER env registration (MXTPU_ZERO3_*)
from . import planner  # noqa: F401 — EAGER env registration (MXTPU_PLAN_*)
from .planner import ShardingPlan
from .spmd_module import SPMDModule
from . import ring_attention
from .ring_attention import ring_attention as ring_attention_fn
from . import pipeline
from .pipeline import pipeline_apply, stack_stage_params
from . import moe
from .moe import moe_ffn, moe_init, moe_shardings
