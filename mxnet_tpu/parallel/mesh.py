"""Device-mesh construction (jax.sharding.Mesh) with named axes.

The mental model follows the public scaling playbook (jax-ml
"How to Scale Your Model"): pick a mesh, annotate shardings, let XLA insert
collectives.  Axis names used throughout the framework:
``dp`` data, ``tp`` tensor, ``sp`` sequence/context, ``pp`` pipeline,
``ep`` expert.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["build_mesh", "default_mesh", "local_mesh", "AXIS_DP", "AXIS_TP",
           "AXIS_SP", "AXIS_PP", "AXIS_EP"]

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_PP = "pp"
AXIS_EP = "ep"


def build_mesh(axis_sizes, devices=None):
    """Build a Mesh from {'dp': 4, 'tp': 2, ...}.

    Axis order follows insertion order; sizes must multiply to the device
    count.  Later axes are placed innermost so e.g. 'tp' lands on
    adjacent chips (best ICI locality for the heaviest collectives).
    """
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(s) for s in axis_sizes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise MXNetError(
            "mesh axes %s multiply to %d but %d devices are available"
            % (dict(axis_sizes), n, len(devices)))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def default_mesh(data_parallel=None, tensor_parallel=1, sequence_parallel=1,
                 devices=None):
    """Default mesh: everything not claimed by tp/sp goes to dp."""
    import jax
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data_parallel is None:
        data_parallel = n // (tensor_parallel * sequence_parallel)
    axes = {AXIS_DP: data_parallel}
    if sequence_parallel > 1:
        axes[AXIS_SP] = sequence_parallel
    if tensor_parallel > 1:
        axes[AXIS_TP] = tensor_parallel
    return build_mesh(axes, devices)


def local_mesh(axis_name=AXIS_DP, devices=None):
    """1-D mesh over all local devices (the kvstore='tpu' default)."""
    import jax
    if devices is None:
        devices = jax.devices()
    return build_mesh({axis_name: len(devices)}, devices)
