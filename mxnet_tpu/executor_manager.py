"""Batch-slicing helpers + legacy DataParallelExecutorManager
(reference python/mxnet/executor_manager.py)."""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError

__all__ = ["_split_input_slice", "_load_data", "_load_label",
           "DataParallelExecutorManager"]


def _split_input_slice(batch_size, work_load_list):
    """Split a batch into per-device slices proportional to workload
    (reference executor_manager.py:15)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size smaller than number of devices")
    slices = []
    start = 0
    for i, load in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * load / float(total)))
        slices.append(slice(start, end))
        start = end
    return slices


def _load_general(data, targets, batch_axis=0):
    """Copy host batch slices into per-device arrays (reference
    executor_group.py:_load_general)."""
    from .ndarray import _to_device
    for d_src, d_targets in zip(data, targets):
        for slice_idx, d_dst in d_targets:
            if batch_axis == 0:
                src = d_src[slice_idx]
            else:
                idx = [slice(None)] * batch_axis + [slice_idx]
                src = d_src[tuple(idx)]
            raw = src._data if hasattr(src, "_data") else src
            d_dst._data = _to_device(raw.astype(d_dst._data.dtype), d_dst._ctx)


def _load_data(batch, targets, batch_axis=0):
    _load_general(batch.data, targets, batch_axis)


def _load_label(batch, targets, batch_axis=0):
    _load_general(batch.label, targets, batch_axis)


class DataParallelExecutorManager(object):
    """Legacy manager used by model.FeedForward (reference
    executor_manager.py:DataParallelExecutorManager).  Thin adapter over
    module.DataParallelExecutorGroup."""

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None, sym_gen=None):
        from .module.executor_group import DataParallelExecutorGroup
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list,
            [(d.name, d.shape) for d in train_data.provide_data],
            [(l.name, l.shape) for l in train_data.provide_label],
            param_names, for_training=True, inputs_need_grad=False)

    def install_monitor(self, monitor):
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        self._cur_batch = data_batch

    def forward(self, is_train=False):
        self.execgrp.forward(self._cur_batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)
