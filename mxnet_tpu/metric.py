"""Evaluation metrics (reference python/mxnet/metric.py, 470 LoC)."""
from __future__ import annotations

import math

import numpy as _numpy

from .base import MXNetError, Registry
from .base import register_env as _register_env
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Perplexity",
           "MAE", "MSE", "RMSE", "CrossEntropy", "Loss", "Torch", "Caffe",
           "CustomMetric", "CompositeEvalMetric", "SkippedSteps", "np",
           "create", "try_install_deferred",
           "ENV_METRIC_INTERVAL", "ENV_METRIC_BLOCKING"]

metric_registry = Registry("metric")


def check_label_shapes(labels, preds, shape=False):
    if shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape[0], preds.shape[0]
    if label_shape != pred_shape:
        raise ValueError("Shape of labels %d does not match shape of "
                         "predictions %d" % (label_shape, pred_shape))


class EvalMetric(object):
    """Base metric (reference metric.py:EvalMetric).

    Deferred device accumulation: a fused trainer can keep this metric's
    (sum, count) IN-GRAPH (``SPMDTrainer.install_metric``) so per-step
    ``update`` calls never force a device->host sync.  The trainer is
    attached as a deferred source (:meth:`attach_deferred_source`); any
    ``get()``/``reset()`` first folds the device-side totals in, so reads
    are always exact — between reads the host copy lags by at most the
    fetch interval (MXTPU_METRIC_INTERVAL).
    """

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
        reset_fn = getattr(self, "_deferred_reset", None)
        if reset_fn is not None:
            reset_fn()

    # -- deferred (in-graph) accumulation ----------------------------------
    def graph_update(self, label_names):
        """A jax-traceable ``fn(outs, data) -> (sum, count)`` mirroring
        :meth:`update` for in-graph accumulation, or None when this metric
        has no device-side rule (the caller then stays on the blocking
        host path).  ``outs`` is the step's output list; ``data`` the
        pre-transform input dict (labels under ``label_names``)."""
        return None

    def attach_deferred_source(self, fetch, reset):
        """Fold device-side accumulators into this metric lazily:
        ``fetch() -> (sum_delta, count_delta)`` is drained on every
        ``get``/explicit fold; ``reset()`` zeroes the device side when the
        metric resets."""
        self._deferred_fetch = fetch
        self._deferred_reset = reset

    def detach_deferred_source(self):
        self._deferred_fetch = None
        self._deferred_reset = None

    def fold_deferred(self):
        """Drain any pending device-side (sum, count) into the host
        accumulators (one small device->host read; no-op when no deferred
        source is attached)."""
        fetch = getattr(self, "_deferred_fetch", None)
        if fetch is None:
            return
        s, c = fetch()
        if c:
            self.sum_metric += s
            self.num_inst += int(c)

    def get(self):
        self.fold_deferred()
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [x / y if y != 0 else float("nan")
                  for x, y in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics (reference metric.py:CompositeEvalMetric)."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = metrics if metrics is not None else []

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str) else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, results = [], []
        for metric in self.metrics:
            name, result = metric.get()
            names.append(name)
            results.append(result)
        return names, results


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _numpy.asarray(x)


@metric_registry.register(aliases=("acc",))
class Accuracy(EvalMetric):
    """Classification accuracy (reference metric.py:Accuracy)."""

    def __init__(self, axis=1, name="accuracy"):
        super().__init__(name)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            check_label_shapes(label, pred)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)

    def graph_update(self, label_names):
        """In-graph (sum, count) rule — integer counts in f32, so the
        deferred totals are bit-identical to the host path's."""
        if not label_names:
            return None
        axis = self.axis

        def fn(outs, data):
            import jax.numpy as jnp
            s = jnp.float32(0.0)
            c = jnp.float32(0.0)
            for name, pred in zip(label_names, outs):
                label = data[name]
                if pred.ndim > label.ndim:
                    pred = jnp.argmax(pred, axis=axis)
                pred = pred.astype(jnp.int32).reshape(-1)
                label = label.astype(jnp.int32).reshape(-1)
                s = s + jnp.sum(pred == label).astype(jnp.float32)
                c = c + jnp.float32(label.shape[0])
            return s, c

        return fn


@metric_registry.register(name="top_k_accuracy", aliases=("topkaccuracy",))
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference metric.py:TopKAccuracy)."""

    def __init__(self, top_k=1, **kwargs):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            pred = _numpy.argsort(pred, axis=1)
            num_samples, num_classes = pred.shape
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += \
                    (pred[:, num_classes - 1 - j].astype("int32") ==
                     label.astype("int32")).sum()
            self.num_inst += num_samples


@metric_registry.register
class F1(EvalMetric):
    """Binary F1 (reference metric.py:F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype("int32")
            pred_label = _numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(_numpy.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary "
                                 "classification.")
            tp = ((pred_label == 1) & (label == 1)).sum()
            fp = ((pred_label == 1) & (label == 0)).sum()
            fn = ((pred_label == 0) & (label == 1)).sum()
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            if precision + recall > 0:
                f1 = 2 * precision * recall / (precision + recall)
            else:
                f1 = 0.0
            self.sum_metric += f1
            self.num_inst += 1


@metric_registry.register
class Perplexity(EvalMetric):
    """Perplexity (reference metric.py:Perplexity)."""

    def __init__(self, ignore_label=None, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).reshape(-1).astype("int32")
            pred = _as_np(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _numpy.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _numpy.sum(_numpy.log(_numpy.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        self.fold_deferred()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@metric_registry.register
class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += _numpy.abs(label - pred).mean()
            self.num_inst += 1


@metric_registry.register
class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@metric_registry.register
class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += _numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@metric_registry.register(name="ce", aliases=("crossentropy",))
class CrossEntropy(EvalMetric):
    """Cross entropy over class-probability outputs (metric.py:CrossEntropy)."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[_numpy.arange(label.shape[0]), _numpy.int32(label)]
            self.sum_metric += (-_numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@metric_registry.register
class Loss(EvalMetric):
    """Mean of the output values (for MakeLoss-style outputs)."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += _as_np(pred).sum()
            self.num_inst += pred.size


class Torch(Loss):
    def __init__(self, name="torch"):
        super(Loss, self).__init__(name)


class Caffe(Torch):
    def __init__(self):
        super(Loss, self).__init__("caffe")


class CustomMetric(EvalMetric):
    """Wrap a python feval function (reference metric.py:CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds, shape=True)
        for pred, label in zip(preds, labels):
            label, pred = _as_np(label), _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


class SkippedSteps(EvalMetric):
    """Surfaces the fused step guard's skipped-update counter as a metric
    row, so NaN-skips show up in the same epoch logs as accuracy/loss.

    ``source`` is anything exposing the counter — a Module
    (``skipped_update_count``) or an SPMDTrainer (``skipped_steps``).
    The value is a monotone total, not a per-batch average; ``reset()``
    keeps it (the counter belongs to the trainer, not the metric).

    Deferred-metric interaction: the skip counters live in-graph and the
    source's counter PROPERTY flushes them on read, so ``get()`` is
    always exact even when metric fetches are deferred — between reads
    the host copy is stale by at most the trainer's ``flush_interval``
    (MXTPU_METRIC_INTERVAL) steps.
    """

    def __init__(self, source, name="skipped_steps"):
        self._source = source
        super().__init__(name)

    def update(self, labels, preds):
        pass

    def reset(self):
        pass

    def _count(self):
        for attr in ("skipped_update_count", "skipped_steps"):
            v = getattr(self._source, attr, None)
            if v is not None:
                return float(v)
        return 0.0

    def get(self):
        return (self.name, self._count())


#: fold the device-side accumulators into the host metric every N
#: ``update_metric`` calls; 0 (default) folds only at epoch end / on get()
ENV_METRIC_INTERVAL = _register_env(
    "MXTPU_METRIC_INTERVAL", default=0,
    doc="Fold deferred in-graph train-metric accumulators into the host "
        "metric every N update_metric calls (0 = on reads only)")
#: "1" disables deferred metrics entirely — every step updates the host
#: metric from fetched outputs (the exact-parity blocking mode for tests)
ENV_METRIC_BLOCKING = _register_env(
    "MXTPU_METRIC_BLOCKING", default=0,
    doc="1 disables deferred metrics: every step updates the host metric "
        "from fetched outputs (exact-parity mode for tests)")


def try_install_deferred(trainer, metric):
    """Move ``metric``'s accumulation into ``trainer``'s fused step when
    possible.  Returns the fold interval (int, possibly 0 = epoch-end
    only) when installed, or None when the blocking path must be used
    (no trainer, MXTPU_METRIC_BLOCKING=1, composite/multi-slot metric, or
    a metric without an in-graph rule).

    Call BEFORE the first step (fit does) — installation rebuilds the
    step function, which is free pre-compile and one recompile after."""
    from .base import get_env
    if trainer is None or getattr(trainer, "_step_fn", None) is None:
        return None
    if str(get_env(ENV_METRIC_BLOCKING, "0")) == "1":
        return None
    if getattr(trainer, "compute_dtype", None) is not None:
        # _shard_batch casts floating LABELS to the compute dtype too, and
        # e.g. bf16 cannot represent odd class ids above 256 — the
        # in-graph comparison would silently diverge from the blocking
        # path's exact host labels, breaking the bit-parity contract
        return None
    if not isinstance(metric, EvalMetric) or metric.num is not None:
        return None
    fn = metric.graph_update(list(trainer.label_names))
    if fn is None:
        return None
    interval = int(get_env(ENV_METRIC_INTERVAL, "0"))
    # equivalence key: re-installing the same rule (a second fit() with
    # the same metric config) must not rebuild — and recompile — the step
    key = (type(metric).__name__, getattr(metric, "axis", None),
           tuple(trainer.label_names), interval)
    trainer.install_metric(fn, flush_interval=interval, key=key)
    metric.attach_deferred_source(trainer.fetch_metric,
                                  trainer.reset_metric)
    return interval


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy function (reference metric.py:np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create by name / callable / list (reference metric.py:create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, **kwargs))
        return composite
    if isinstance(metric, str):
        return metric_registry.create(metric, **kwargs)
    raise MXNetError("invalid metric spec %r" % (metric,))
