"""Operator registry and op implementations (JAX lowerings)."""
from .registry import OP_REGISTRY, OpDef, apply_op, get_op, list_ops, register
from . import tensor  # noqa: F401 — registers tensor ops

from . import nn       # noqa: F401 — registers neural layer ops
from . import vision   # noqa: F401 — ROIPooling/SpatialTransformer/...
from . import contrib  # noqa: F401 — MultiBox/Proposal/fft/count_sketch
from . import image_io  # noqa: F401 — imdecode/imresize/copyMakeBorder
from . import ctc      # noqa: F401 — WarpCTC/ctc_loss
