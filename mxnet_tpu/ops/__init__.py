"""Operator registry and op implementations (JAX lowerings)."""
from .registry import OP_REGISTRY, OpDef, apply_op, get_op, list_ops, register
from . import tensor  # noqa: F401 — registers tensor ops

try:  # neural layer ops (registered on import)
    from . import nn  # noqa: F401
except ImportError:  # pragma: no cover - during bootstrap
    pass
try:
    from . import contrib  # noqa: F401
except ImportError:  # pragma: no cover
    pass
