"""Neural-network layer ops.

TPU-native re-design of the reference's legacy ``OperatorProperty`` layers
(src/operator/*.cc — Convolution, FullyConnected, BatchNorm, Pooling, ...).
Where the reference dispatches to cuDNN/mshadow CUDA kernels, these lower to
lax convolutions / reduce_windows / dot_generals that XLA tiles onto the
MXU; loss layers reproduce the reference's custom backward semantics via
jax.custom_vjp; stateful aux (BatchNorm moving stats) is returned
functionally and written back by the executor.

Each layer carrying learnable parameters provides ``infer_shape`` so that
partial shape information propagates exactly like the reference's
InferShape (weights back-inferred from data shape + attrs).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError


def _tuple(x, n=None):
    if isinstance(x, (list, tuple)):
        t = tuple(x)
    else:
        t = (x,)
    if n is not None and len(t) == 1 and n > 1:
        t = t * n
    return t


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


# ---------------------------------------------------------------------------
# FullyConnected — src/operator/fully_connected-inl.h
# ---------------------------------------------------------------------------

def _fc_inputs(attrs):
    if attrs.get("no_bias", False):
        return ("data", "weight")
    return ("data", "weight", "bias")


def _fc_infer(attrs, in_shapes):
    num_hidden = int(attrs["num_hidden"])
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    in_dim = _prod(data[1:])
    shapes = [tuple(data), (num_hidden, in_dim)]
    if not attrs.get("no_bias", False):
        shapes.append((num_hidden,))
    return shapes, [(data[0], num_hidden)], []


@register("FullyConnected", input_names=_fc_inputs, infer_shape=_fc_infer)
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False):
    """y = x @ W.T + b with input flattened to 2D (reference
    src/operator/fully_connected-inl.h Forward).  Direct MXU matmul."""
    x = data.reshape((data.shape[0], -1))
    out = jnp.dot(x, weight.T)
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution — src/operator/convolution-inl.h (cuDNN in the reference;
# here lax.conv_general_dilated → MXU)
# ---------------------------------------------------------------------------

_CONV_DIMNUMS = {1: ("NCH", "OIH", "NCH"),
                 2: ("NCHW", "OIHW", "NCHW"),
                 3: ("NCDHW", "OIDHW", "NCDHW")}


def _conv_infer(attrs, in_shapes):
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    num_filter = int(attrs["num_filter"])
    num_group = int(attrs.get("num_group", 1))
    no_bias = attrs.get("no_bias", False)
    stride = _tuple(attrs.get("stride", (1,) * nd), nd)
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    dilate = _tuple(attrs.get("dilate", (1,) * nd), nd)
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    c_in = data[1]
    wshape = (num_filter, c_in // num_group) + kernel
    shapes = [tuple(data), wshape] + ([] if no_bias else [(num_filter,)])
    out_sp = tuple(
        (data[2 + i] + 2 * pad[i] - (dilate[i] * (kernel[i] - 1) + 1)) // stride[i] + 1
        for i in range(nd))
    return shapes, [(data[0], num_filter) + out_sp], []


@register("Convolution", input_names=_fc_inputs, infer_shape=_conv_infer,
          aliases=("Convolution_v1",))
def convolution(data, weight, bias=None, kernel=(), stride=None, dilate=None,
                pad=None, num_filter=0, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None):
    kernel = _tuple(kernel)
    nd = len(kernel)
    stride = _tuple(stride or (1,) * nd, nd)
    dilate = _tuple(dilate or (1,) * nd, nd)
    pad = _tuple(pad if pad is not None else (0,) * nd, nd)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMNUMS[nd])
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        feature_group_count=num_group, dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Deconvolution — src/operator/deconvolution-inl.h
# ---------------------------------------------------------------------------

def _deconv_infer(attrs, in_shapes):
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    num_filter = int(attrs["num_filter"])
    num_group = int(attrs.get("num_group", 1))
    no_bias = attrs.get("no_bias", True)
    stride = _tuple(attrs.get("stride", (1,) * nd), nd)
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    adj = _tuple(attrs.get("adj", (0,) * nd), nd)
    dilate = _tuple(attrs.get("dilate", (1,) * nd), nd)
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    c_in = data[1]
    wshape = (c_in, num_filter // num_group) + kernel
    shapes = [tuple(data), wshape] + ([] if no_bias else [(num_filter,)])
    out_sp = tuple(
        stride[i] * (data[2 + i] - 1) + dilate[i] * (kernel[i] - 1) + 1
        - 2 * pad[i] + adj[i]
        for i in range(nd))
    return shapes, [(data[0], num_filter) + out_sp], []


@register("Deconvolution",
          input_names=lambda attrs: (("data", "weight") if attrs.get("no_bias", True)
                                     else ("data", "weight", "bias")),
          infer_shape=_deconv_infer)
def deconvolution(data, weight, bias=None, kernel=(), stride=None, pad=None,
                  adj=None, dilate=None, num_filter=0, num_group=1,
                  no_bias=True, workspace=512, target_shape=None,
                  cudnn_tune=None, cudnn_off=False, layout=None):
    """Transposed convolution = gradient of Convolution w.r.t. its input
    (reference implements it exactly that way via the conv backward kernel)."""
    kernel = _tuple(kernel)
    nd = len(kernel)
    stride = _tuple(stride or (1,) * nd, nd)
    pad = _tuple(pad if pad is not None else (0,) * nd, nd)
    adj = _tuple(adj if adj is not None else (0,) * nd, nd)
    dilate = _tuple(dilate if dilate is not None else (1,) * nd, nd)
    # lhs-dilated conv with flipped kernel implements conv-transpose;
    # effective kernel extent accounts for rhs dilation
    keff = [dilate[i] * (kernel[i] - 1) + 1 for i in range(nd)]
    padding = [(keff[i] - 1 - pad[i], keff[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    flipped = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    # weight layout is (C_in, num_filter//group, k...) → swap to OIHW w.r.t.
    # the transposed conv
    if num_group == 1:
        w = jnp.swapaxes(flipped, 0, 1)
    else:
        ci, co_g = flipped.shape[0], flipped.shape[1]
        w = flipped.reshape((num_group, ci // num_group, co_g) + kernel)
        w = jnp.swapaxes(w, 1, 2).reshape((num_group * co_g, ci // num_group) + kernel)
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _CONV_DIMNUMS[nd])
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate,
        feature_group_count=num_group, dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling — src/operator/pooling-inl.h (+ pooling_v1)
# ---------------------------------------------------------------------------

def _pool_out_dim(size, k, s, p, convention):
    if convention == "full":
        return int(np.ceil((size + 2 * p - k) / float(s))) + 1
    return (size + 2 * p - k) // s + 1


def _pool_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    if attrs.get("global_pool", False):
        return [tuple(data)], [tuple(data[:2]) + (1,) * (len(data) - 2)], []
    kernel = _tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _tuple(attrs.get("stride", (1,) * nd), nd)
    pad = _tuple(attrs.get("pad", (0,) * nd), nd)
    conv = str(attrs.get("pooling_convention", "valid"))
    out_sp = tuple(_pool_out_dim(data[2 + i], kernel[i], stride[i], pad[i], conv)
                   for i in range(nd))
    return [tuple(data)], [tuple(data[:2]) + out_sp], []


@register("Pooling", infer_shape=_pool_infer, aliases=("Pooling_v1",))
def pooling(data, kernel=(), pool_type="max", stride=None, pad=None,
            global_pool=False, pooling_convention="valid", cudnn_off=False):
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _tuple(kernel)
        stride = _tuple(stride or (1,) * nd, nd)
        pad = _tuple(pad if pad is not None else (0,) * nd, nd)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    if pooling_convention == "full" and not global_pool:
        # ceil-mode: extend right padding so the last window fits
        pads = [(0, 0), (0, 0)]
        for i in range(nd):
            out_d = _pool_out_dim(data.shape[2 + i], kernel[i], stride[i],
                                  pad[i], "full")
            needed = (out_d - 1) * stride[i] + kernel[i] - data.shape[2 + i] - pad[i]
            pads.append((pad[i], max(needed, pad[i])))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pool_type == "max":
        # init must stay a python/numpy scalar literal: the reduce_window
        # max-grad rule inspects it, and a jax-array constant becomes an
        # opaque tracer under jit, killing the VJP
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = np.array(-np.inf, dtype=data.dtype)
        else:
            init = np.array(np.iinfo(data.dtype).min, dtype=data.dtype)
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    summed = lax.reduce_window(data, np.array(0, dtype=data.dtype), lax.add,
                               window, strides, pads)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        # reference mshadow pool divides by the constant kernel size
        # (padding included) — pooling-inl.h
        return summed / _prod(kernel)
    raise MXNetError("unknown pool_type %r" % pool_type)


# ---------------------------------------------------------------------------
# Activation / LeakyReLU — src/operator/activation-inl.h, leaky_relu-inl.h
# ---------------------------------------------------------------------------

@register("Activation")
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise MXNetError("unknown act_type %r" % act_type)


def _lrelu_inputs(attrs):
    if str(attrs.get("act_type", "leaky")) == "prelu":
        return ("data", "gamma")
    return ("data",)


def _lrelu_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    if str(attrs.get("act_type", "leaky")) == "prelu":
        return [tuple(data), (data[1],)], [tuple(data)], []
    return [tuple(data)], [tuple(data)], []


@register("LeakyReLU", input_names=_lrelu_inputs, infer_shape=_lrelu_infer,
          needs_is_train=True, needs_rng=True)
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, is_train=False, rng=None):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        if is_train:
            s = jax.random.uniform(rng, data.shape, dtype=data.dtype,
                                   minval=lower_bound, maxval=upper_bound)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise MXNetError("unknown act_type %r" % act_type)


# ---------------------------------------------------------------------------
# BatchNorm — src/operator/batch_norm-inl.h (aux: moving_mean, moving_var)
# ---------------------------------------------------------------------------

def _bn_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None, None, None], [None, None]
    c = (data[1],)
    return [tuple(data), c, c], [tuple(data), c, c], [c, c]


@register("BatchNorm", input_names=("data", "gamma", "beta"),
          aux_names=("moving_mean", "moving_var"),
          num_outputs=lambda attrs: 3 if attrs.get("output_mean_var", False) else 1,
          output_names=lambda attrs: (("output", "mean", "var")
                                      if attrs.get("output_mean_var", False)
                                      else ("output",)),
          infer_shape=_bn_infer, needs_is_train=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=0.001,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, is_train=False):
    """Batch normalization over the channel axis (axis 1, NCHW).

    Train mode computes batch statistics and returns updated moving stats as
    trailing outputs (the executor writes them back to aux storage — the
    functional equivalent of the reference mutating aux_states in-place).
    """
    axes = (0,) + tuple(range(2, data.ndim))
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if is_train and not use_global_stats:
        mean = jnp.mean(data, axis=axes)
        var = jnp.var(data, axis=axes)
        new_moving_mean = moving_mean * momentum + mean * (1 - momentum)
        new_moving_var = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_moving_mean, new_moving_var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * inv.reshape(bshape) * \
        gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, mean, lax.stop_gradient(inv), new_moving_mean, new_moving_var
    return out, new_moving_mean, new_moving_var


# ---------------------------------------------------------------------------
# InstanceNorm / L2Normalization — src/operator/instance_norm-inl.h,
# l2_normalization-inl.h
# ---------------------------------------------------------------------------

def _in_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    c = (data[1],)
    return [tuple(data), c, c], [tuple(data)], []


@register("InstanceNorm", input_names=("data", "gamma", "beta"),
          infer_shape=_in_infer)
def instance_norm(data, gamma, beta, eps=0.001):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) + \
        beta.reshape(bshape)


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError("unknown mode %r" % mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


# ---------------------------------------------------------------------------
# LRN — src/operator/lrn-inl.h
# ---------------------------------------------------------------------------

@register("LRN", num_outputs=1)
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    windows = sum(
        lax.slice_in_dim(padded, i, i + data.shape[1], axis=1)
        for i in range(nsize))
    norm = jnp.power(knorm + (alpha / nsize) * windows, -beta)
    return data * norm


# ---------------------------------------------------------------------------
# Dropout — src/operator/dropout-inl.h
# ---------------------------------------------------------------------------

@register("Dropout", needs_is_train=True, needs_rng=True,
          num_outputs=1)
def dropout(data, p=0.5, is_train=False, rng=None, mode=None):
    if not is_train or p <= 0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, data.shape)
    return jnp.where(mask, data / keep, jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# Embedding — src/operator/tensor/indexing_op.h (EmbeddingOp)
# ---------------------------------------------------------------------------

def _embed_infer(attrs, in_shapes):
    input_dim = int(attrs["input_dim"])
    output_dim = int(attrs["output_dim"])
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    return [tuple(data), (input_dim, output_dim)], [tuple(data) + (output_dim,)], []


@register("Embedding", input_names=("data", "weight"), infer_shape=_embed_infer)
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32"):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# Concat / SliceChannel — src/operator/concat-inl.h, slice_channel-inl.h
# ---------------------------------------------------------------------------

def _concat_inputs(attrs):
    n = int(attrs.get("num_args", 1))
    return tuple("arg%d" % i for i in range(n))


def _concat_infer(attrs, in_shapes):
    dim = int(attrs.get("dim", 1))
    known = [s for s in in_shapes if s is not None]
    if not known or any(s is None for s in in_shapes):
        return in_shapes, [None], []
    out = list(known[0])
    out[dim] = sum(s[dim] for s in in_shapes)
    return [tuple(s) for s in in_shapes], [tuple(out)], []


@register("Concat", input_names=_concat_inputs, variable_inputs=True,
          infer_shape=_concat_infer, aliases=("concat",))
def concat(*args, num_args=1, dim=1):
    return jnp.concatenate(args, axis=dim)


def _slice_channel_infer(attrs, in_shapes):
    n = int(attrs.get("num_outputs", 1))
    axis = int(attrs.get("axis", 1))
    squeeze = attrs.get("squeeze_axis", False)
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None] * n, []
    out = list(data)
    out[axis] //= n
    if squeeze and out[axis] == 1:
        out.pop(axis)
    return [tuple(data)], [tuple(out)] * n, []


@register("SliceChannel", aliases=("split",),
          num_outputs=lambda attrs: int(attrs.get("num_outputs", 1)),
          infer_shape=_slice_channel_infer)
def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


# ---------------------------------------------------------------------------
# Pad / Crop / UpSampling — src/operator/pad.cc, crop.cc, upsampling.cc
# ---------------------------------------------------------------------------

@register("Pad", aliases=("pad",))
def pad_op(data, pad_width=(), mode="constant", constant_value=0.0):
    pw = _tuple(pad_width)
    pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(data.ndim)]
    if mode == "constant":
        return jnp.pad(data, pads, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pads, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pads, mode="reflect")
    raise MXNetError("unknown pad mode %r" % mode)


def _crop_inputs(attrs):
    n = int(attrs.get("num_args", 1))
    return ("data",) if n == 1 else ("data", "crop_like")


def _crop_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    if int(attrs.get("num_args", 1)) == 2 and in_shapes[1] is not None:
        hw = in_shapes[1][2:]
    else:
        hw = _tuple(attrs.get("h_w", ()))
    out = tuple(data[:2]) + tuple(hw)
    return [tuple(s) if s else s for s in in_shapes], [out], []


@register("Crop", input_names=_crop_inputs, infer_shape=_crop_infer)
def crop(data, crop_like=None, num_args=1, offset=(0, 0), h_w=(0, 0),
         center_crop=False):
    if crop_like is not None:
        h, w = crop_like.shape[2], crop_like.shape[3]
    else:
        h, w = _tuple(h_w, 2)
    if center_crop:
        oy = (data.shape[2] - h) // 2
        ox = (data.shape[3] - w) // 2
    else:
        oy, ox = _tuple(offset, 2)
    return lax.dynamic_slice(data, (0, 0, oy, ox),
                             (data.shape[0], data.shape[1], h, w))


def _upsample_bilinear_filter(scale):
    k = 2 * scale - scale % 2
    center = (2 * scale - 1 - scale % 2) / (2.0 * scale)
    og = np.arange(k)
    f = (1 - np.abs(og / scale - center))
    return (f[:, None] * f[None, :]).astype(np.float32)


def _upsample_infer(attrs, in_shapes):
    scale = int(attrs.get("scale", 1))
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    out = (data[0], data[1], data[2] * scale, data[3] * scale)
    shapes = [tuple(s) if s else s for s in in_shapes]
    if str(attrs.get("sample_type", "nearest")) == "bilinear":
        k = 2 * scale - scale % 2
        nf = int(attrs.get("num_filter", data[1]) or data[1])
        shapes = [tuple(data), (nf, 1, k, k)]
        out = (data[0], nf, data[2] * scale, data[3] * scale)
    return shapes, [out], []


def _upsample_inputs(attrs):
    if str(attrs.get("sample_type", "nearest")) == "bilinear":
        return ("data", "weight")
    return _concat_inputs(attrs)


@register("UpSampling", variable_inputs=True, input_names=_upsample_inputs,
          infer_shape=_upsample_infer)
def upsampling(*args, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    """Nearest: integer repeat.  Bilinear: grouped transposed conv with the
    (learnable) weight input, kernel 2*scale-scale%2, stride scale — exactly
    the reference's UpSamplingBilinear (src/operator/upsampling-inl.h)."""
    if sample_type == "bilinear":
        data, weight = args[0], args[1]
        k = 2 * scale - scale % 2
        p = int(np.ceil((scale - 1) / 2.0))
        nf = num_filter or data.shape[1]
        # deconv weight layout is (C_in, nf/group, k, k); group == C
        w = jnp.reshape(weight, (data.shape[1], 1, k, k))
        return deconvolution(data, w, None, kernel=(k, k),
                             stride=(scale, scale), pad=(p, p),
                             num_filter=nf, num_group=data.shape[1],
                             no_bias=True)
    outs = []
    for data in args:
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        return sum(outs)
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# Loss layers with custom backward — softmax_output-inl.h,
# regression_output-inl.h, make_loss-inl.h, svm_output-inl.h
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _softmax_output(data, label, grad_scale, ignore_label, use_ignore,
                    multi_output, normalization, out_grad):
    return _softmax_fwd_only(data, multi_output)


def _softmax_fwd_only(data, multi_output):
    if multi_output and data.ndim > 2:
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization, out_grad):
    out = _softmax_fwd_only(data, multi_output)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, multi_output,
                        normalization, out_grad, res, g):
    out, label = res
    axis = 1 if (multi_output and out.ndim > 2) else out.ndim - 1
    if label.shape == out.shape:
        grad = out - label
        valid = jnp.asarray(out.shape[0], out.dtype)
    else:
        idx = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, out.shape[axis], axis=axis, dtype=out.dtype)
        grad = out - onehot
        if use_ignore:
            mask = (idx != int(ignore_label)).astype(out.dtype)
            grad = grad * jnp.expand_dims(mask, axis)
            valid = jnp.maximum(jnp.sum(mask), 1.0)
        else:
            valid = jnp.asarray(float(np.prod(label.shape)), out.dtype)
    scale = grad_scale
    if normalization == "batch":
        grad = grad * (scale / out.shape[0])
    elif normalization == "valid":
        grad = grad * scale / valid
    else:
        grad = grad * scale
    if out_grad:
        # reference softmax_output-inl.h:127-129,220-224: with out_grad=True
        # the label-based gradient is modulated elementwise by the incoming
        # head gradient (policy-gradient / custom-loss escape hatch)
        grad = grad * g
    return grad, jnp.zeros_like(label)


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


def _loss_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    label = in_shapes[1] if len(in_shapes) > 1 and in_shapes[1] is not None \
        else (data[0],)
    return [tuple(data), tuple(label)], [tuple(data)], []


def _softmax_label_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    if attrs.get("multi_output", False) and len(data) > 2:
        label = (data[0],) + tuple(data[2:])
    else:
        label = tuple(data[:-1])
    if len(in_shapes) > 1 and in_shapes[1] is not None:
        label = tuple(in_shapes[1])
    return [tuple(data), label], [tuple(data)], []


@register("SoftmaxOutput", input_names=("data", "label"),
          infer_shape=_softmax_label_infer, aliases=("Softmax_",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Softmax forward; backward = (p - onehot(label)) * grad_scale, ignoring
    incoming head gradient — reference src/operator/softmax_output-inl.h."""
    return _softmax_output(data, label, float(grad_scale), float(ignore_label),
                           bool(use_ignore), bool(multi_output),
                           str(normalization), bool(out_grad))


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _make_regression(name, fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _core(data, label, grad_scale):
        return fwd_fn(data)

    def _fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label)

    def _bwd(grad_scale, res, g):
        out, label = res
        num_output = _prod(label.shape[1:]) if label.ndim > 1 else 1
        grad = (grad_scale / num_output) * bwd_fn(out, label.reshape(out.shape))
        return grad, jnp.zeros_like(label)

    _core.defvjp(_fwd, _bwd)

    @register(name, input_names=("data", "label"), infer_shape=_loss_infer)
    def _op(data, label, grad_scale=1.0):
        return _core(data, label, float(grad_scale))
    _op.__name__ = name
    return _op


_make_regression("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)
_make_regression("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output(data, label, margin, regularization_coefficient, use_linear):
    return data


def _svm_fwd(data, label, margin, regularization_coefficient, use_linear):
    return data, (data, label)


def _svm_bwd(margin, reg, use_linear, res, g):
    """One-vs-all hinge exactly as the reference kernels (svm_output.cc
    L1_SVM/L2_SVM): the true class's score is pushed above +margin, every
    other class's score below -margin; incoming head gradient is ignored
    (loss-layer convention)."""
    data, label = res
    idx = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(idx, data.shape[1], dtype=data.dtype)
    if use_linear:
        g_true = jnp.where(data < margin, -reg, 0.0)
        g_other = jnp.where(data > -margin, reg, 0.0)
    else:
        g_true = jnp.where(data < margin, -2.0 * reg * (margin - data), 0.0)
        g_other = jnp.where(data > -margin, 2.0 * reg * (margin + data), 0.0)
    grad = onehot * g_true + (1 - onehot) * g_other
    return grad.astype(data.dtype), jnp.zeros_like(label)


_svm_output.defvjp(_svm_fwd, _svm_bwd)


@register("SVMOutput", input_names=("data", "label"), infer_shape=_loss_infer)
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    return _svm_output(data, label, float(margin),
                       float(regularization_coefficient), bool(use_linear))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _make_loss_core(data, grad_scale, normalization):
    return data


def _make_loss_fwd(data, grad_scale, normalization):
    return data, data.shape


def _make_loss_bwd(grad_scale, normalization, shape, g):
    scale = grad_scale
    if normalization == "batch":
        scale = scale / shape[0]
    elif normalization == "valid":
        scale = scale / _prod(shape)
    return (jnp.full(shape, scale, dtype=g.dtype),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Forward identity; backward emits grad_scale (reference
    src/operator/make_loss-inl.h:92-98)."""
    return _make_loss_core(data, float(grad_scale), str(normalization))


@register("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001,
                                  momentum=0.9):
    return data  # regularization gradient omitted (matches fwd semantics)


# ---------------------------------------------------------------------------
# Sequence ops — src/operator/sequence_{last,mask,reverse}-inl.h
# layouts: data is (seq_len, batch, ...) like the reference
# ---------------------------------------------------------------------------

def _seq_inputs(attrs):
    if attrs.get("use_sequence_length", False):
        return ("data", "sequence_length")
    return ("data",)


def _seq_last_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None], []
    return [tuple(s) if s else s for s in in_shapes], [tuple(data[1:])], []


@register("SequenceLast", input_names=_seq_inputs, infer_shape=_seq_last_infer)
def sequence_last(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]


@register("SequenceMask", input_names=_seq_inputs)
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0):
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[0]
    steps = jnp.arange(T).reshape((T,) + (1,) * (data.ndim - 1))
    lens = sequence_length.reshape((1, -1) + (1,) * (data.ndim - 2))
    return jnp.where(steps < lens, data, jnp.asarray(value, data.dtype))


@register("SequenceReverse", input_names=_seq_inputs)
def sequence_reverse(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)


# ---------------------------------------------------------------------------
# Fused RNN — src/operator/rnn-inl.h / cudnn_rnn-inl.h.
# TPU-native: lax.scan over time with gates batched into single MXU matmuls.
# Weight layout matches the reference's fused vector format so
# rnn_cell pack/unpack round-trips (python/mxnet/rnn/rnn_cell.py:541-607):
# per layer, per direction: all i2h weights (gates stacked), all h2h weights,
# then per layer/direction all i2h biases, all h2h biases.
# Gate order: LSTM [i, f, c, o]; GRU [r, z, n].
# ---------------------------------------------------------------------------

_RNN_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    gates = _RNN_GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (in_sz + state_size + 2)
    return size


def _rnn_split_params(params, num_layers, input_size, state_size,
                      bidirectional, mode):
    """Split the fused 1-D parameter vector into per-layer weight matrices."""
    gates = _RNN_GATES[mode]
    dirs = 2 if bidirectional else 1
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        layer_w = []
        for d in range(dirs):
            n_i2h = gates * state_size * in_sz
            w_i2h = params[off:off + n_i2h].reshape(gates * state_size, in_sz)
            off += n_i2h
            n_h2h = gates * state_size * state_size
            w_h2h = params[off:off + n_h2h].reshape(gates * state_size, state_size)
            off += n_h2h
            layer_w.append((w_i2h, w_h2h))
        ws.append(layer_w)
    for layer in range(num_layers):
        layer_b = []
        for d in range(dirs):
            b_i2h = params[off:off + gates * state_size]
            off += gates * state_size
            b_h2h = params[off:off + gates * state_size]
            off += gates * state_size
            layer_b.append((b_i2h, b_h2h))
        bs.append(layer_b)
    return ws, bs


def _rnn_cell_step(mode, state_size):
    # MXTPU_FUSED_KERNELS routing is resolved ONCE per trace (this
    # factory runs at trace time): the fused cell does all gate math in
    # one kernel pass (mxnet_tpu/kernels/lstm_cell.py — Pallas on TPU,
    # fused-lax elsewhere, bit-identical op order either way)
    fused_lstm = None
    if mode == "lstm":
        from ..kernels import fused_enabled
        if fused_enabled("lstm_cell"):
            from ..kernels.lstm_cell import lstm_cell as fused_lstm

    def step(carry, x_proj, w_h2h, b_h2h):
        if mode == "lstm":
            h, c = carry
            gates = x_proj + jnp.dot(h, w_h2h.T) + b_h2h
            if fused_lstm is not None:
                h, c = fused_lstm(gates, c)
                return (h, c), h
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h
        if mode == "gru":
            h = carry[0]
            hp = jnp.dot(h, w_h2h.T) + b_h2h
            xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h = (1 - z) * n + z * h
            return (h,), h
        h = carry[0]
        pre = x_proj + jnp.dot(h, w_h2h.T) + b_h2h
        h = jax.nn.relu(pre) if mode == "rnn_relu" else jnp.tanh(pre)
        return (h,), h
    return step


def _rnn_inputs(attrs):
    mode = str(attrs.get("mode", "lstm"))
    if mode == "lstm":
        return ("data", "parameters", "state", "state_cell")
    return ("data", "parameters", "state")


def _rnn_num_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if str(attrs.get("mode", "lstm")) == "lstm" else 2


def _rnn_infer(attrs, in_shapes):
    mode = str(attrs.get("mode", "lstm"))
    num_layers = int(attrs.get("num_layers", 1))
    state_size = int(attrs.get("state_size"))
    bi = attrs.get("bidirectional", False)
    dirs = 2 if bi else 1
    data = in_shapes[0]
    if data is None:
        return in_shapes, [None] * _rnn_num_outputs(attrs), []
    T, N, I = data
    psize = rnn_param_size(num_layers, I, state_size, bi, mode)
    sshape = (num_layers * dirs, N, state_size)
    shapes = [tuple(data), (psize,), sshape]
    if mode == "lstm":
        shapes.append(sshape)
    outs = [(T, N, state_size * dirs)]
    if attrs.get("state_outputs", False):
        outs.append(sshape)
        if mode == "lstm":
            outs.append(sshape)
    return shapes, outs, []


@register("RNN", input_names=_rnn_inputs, num_outputs=_rnn_num_outputs,
          infer_shape=_rnn_infer, needs_is_train=True, needs_rng=True)
def rnn(data, parameters, state, state_cell=None, state_size=0, num_layers=1,
        bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None, is_train=False,
        rng=None):
    """Fused multi-layer RNN (reference src/operator/cudnn_rnn-inl.h).

    lax.scan over time; all gate projections for a timestep are one MXU
    matmul.  The input projection for the whole sequence is hoisted out of
    the scan (a single (T*N, I) x (I, G*H) matmul) — the TPU-idiomatic
    version of cuDNN's fused RNN.
    """
    T, N, _ = data.shape
    dirs = 2 if bidirectional else 1
    gates = _RNN_GATES[mode]
    ws, bs = _rnn_split_params(parameters, num_layers, data.shape[2],
                               state_size, bidirectional, mode)
    step = _rnn_cell_step(mode, state_size)

    h0 = state.reshape(num_layers, dirs, N, state_size)
    c0 = state_cell.reshape(num_layers, dirs, N, state_size) \
        if state_cell is not None else None

    layer_in = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            w_i2h, w_h2h = ws[layer][d]
            b_i2h, b_h2h = bs[layer][d]
            seq = layer_in if d == 0 else jnp.flip(layer_in, axis=0)
            x_proj = jnp.einsum("tni,gi->tng", seq, w_i2h) + b_i2h
            if mode == "lstm":
                carry0 = (h0[layer, d], c0[layer, d])
            else:
                carry0 = (h0[layer, d],)

            def scan_fn(carry, xp, _w=w_h2h, _b=b_h2h):
                return step(carry, xp, _w, _b)

            carry, hs = lax.scan(scan_fn, carry0, x_proj)
            if d == 1:
                hs = jnp.flip(hs, axis=0)
            outs_dir.append(hs)
            h_finals.append(carry[0])
            if mode == "lstm":
                c_finals.append(carry[1])
        layer_in = outs_dir[0] if dirs == 1 else jnp.concatenate(outs_dir, axis=-1)
        if is_train and p > 0 and layer < num_layers - 1:
            keep = 1.0 - p
            mask = jax.random.bernoulli(jax.random.fold_in(rng, layer), keep,
                                        layer_in.shape)
            layer_in = jnp.where(mask, layer_in / keep, 0.0)

    if not state_outputs:
        return layer_in
    h_out = jnp.stack(h_finals).reshape(num_layers * dirs, N, state_size)
    if mode == "lstm":
        c_out = jnp.stack(c_finals).reshape(num_layers * dirs, N, state_size)
        return layer_in, h_out, c_out
    return layer_in, h_out
