"""CTC loss — the reference's warpctc plugin rebuilt as an XLA lowering
(reference plugin/warpctc/warpctc-inl.h: WarpCTC op over baidu warp-ctc;
example/warpctc/toy_ctc.py is the canonical workload).

TPU-first design: the forward-backward recursion is a `lax.scan` over time
in log space — one compiled kernel, no host round trips, differentiable by
JAX's scan autodiff.  The reference computes grad = softmax - alignment
posteriors inside warp-ctc's C kernel; autodiff through the log-likelihood
produces exactly that quantity, so the backward needs no hand-derived
beta pass.

Conventions match the reference plugin:
  - blank label id = 0 (warpctc-inl.h: info.blank_label = 0)
  - `label` entries equal to 0 are padding and are compacted out
    (labelLengths/removeBlank, warpctc-inl.h:84-109)
  - WarpCTC input `data` is (T*N, alphabet) time-major flattened, output
    is softmax(data); backward writes the CTC gradient wrt activations and
    IGNORES the incoming head gradient (loss-layer convention, like
    SoftmaxOutput)
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

_NEG_INF = -1e30


def _compact_labels(labels):
    """Move non-blank (non-zero) labels to the front of each row, keeping
    order (reference removeBlank), and return (compacted, lengths)."""
    nonblank = labels != 0
    # stable argsort of "is blank" keeps relative order of the kept labels
    order = jnp.argsort(~nonblank, axis=1, stable=True)
    compacted = jnp.take_along_axis(labels, order, axis=1)
    lengths = nonblank.sum(axis=1)
    return compacted, lengths


def ctc_nll(logits, labels):
    """Negative log likelihood of `labels` under CTC with blank=0.

    logits: (T, N, A) unnormalized activations (time-major).
    labels: (N, L) int labels; 0 entries are padding.
    Returns (N,) per-sample losses.  Differentiable; `jax.grad` of the sum
    wrt logits equals warp-ctc's gradient (softmax minus posteriors).
    """
    T, N, A = logits.shape
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    labels, lab_len = _compact_labels(labels.astype(jnp.int32))
    L = labels.shape[1]
    S = 2 * L + 1

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    s_idx = jnp.arange(S)
    valid = s_idx[None, :] < (2 * lab_len + 1)[:, None]          # (N, S)
    # a path may skip ext[s-2] -> ext[s] only between distinct non-blank
    # labels (odd s, different char than two slots back)
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (s_idx[None, :] % 2 == 1) & (ext != ext_m2)        # (N, S)

    emit = jnp.take_along_axis(
        logp.transpose(1, 0, 2), ext[:, None, :].repeat(T, 1), axis=2
    ).transpose(1, 0, 2)                                          # (T, N, S)

    init = jnp.full((N, S), _NEG_INF, jnp.float32)
    init = init.at[:, 0].set(emit[0, :, 0])
    init = init.at[:, 1].set(jnp.where(lab_len > 0, emit[0, :, 1], _NEG_INF))

    def step(alpha, emit_t):
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                     constant_values=_NEG_INF)[:, :S]
        a2 = jnp.where(can_skip,
                       jnp.pad(alpha, ((0, 0), (2, 0)),
                               constant_values=_NEG_INF)[:, :S],
                       _NEG_INF)
        m = jnp.maximum(alpha, jnp.maximum(a1, a2))
        tot = m + jnp.log(jnp.exp(alpha - m) + jnp.exp(a1 - m)
                          + jnp.exp(a2 - m))
        tot = jnp.where(m <= _NEG_INF / 2, _NEG_INF, tot)
        new = jnp.where(valid, tot + emit_t, _NEG_INF)
        return new, None

    alpha, _ = lax.scan(step, init, emit[1:])
    # logZ = logsumexp over the last two valid extended positions
    last = 2 * lab_len                                           # S_n - 1
    aT = alpha
    a_last = jnp.take_along_axis(aT, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        aT, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(lab_len > 0, a_prev, _NEG_INF)
    m = jnp.maximum(a_last, a_prev)
    logz = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
    return -logz


def _ctc_shape(attrs, in_shapes):
    data = in_shapes[0]
    return list(in_shapes), [tuple(data) if data else None], []


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _warpctc_core(data, label, input_length, label_length):
    return jax.nn.softmax(data.astype(jnp.float32), axis=-1)


def _warpctc_fwd(data, label, input_length, label_length):
    return (jax.nn.softmax(data.astype(jnp.float32), axis=-1),
            (data, label))


def _warpctc_bwd(input_length, label_length, res, g):
    data, label = res
    T = input_length
    N = data.shape[0] // T
    A = data.shape[1]
    logits = data.reshape(T, N, A)
    labels = label.reshape(N, label_length).astype(jnp.int32)
    grad3 = jax.grad(lambda lg: ctc_nll(lg, labels).sum())(logits)
    # warp-ctc writes d(sum cost)/d(activations) directly, ignoring the
    # incoming head gradient (warpctc-inl.h Backward)
    if jnp.issubdtype(jnp.asarray(label).dtype, jnp.integer):
        # integer primals take a float0 cotangent under custom_vjp
        label_ct = np.zeros(np.shape(label), dtype=jax.dtypes.float0)
    else:
        label_ct = jnp.zeros_like(label)
    return grad3.reshape(T * N, A).astype(data.dtype), label_ct


_warpctc_core.defvjp(_warpctc_fwd, _warpctc_bwd)


@register("WarpCTC", input_names=("data", "label"), infer_shape=_ctc_shape)
def warpctc(data, label, label_length=0, input_length=0):
    """CTC loss layer (reference plugin/warpctc).  data: (T*N, alphabet)
    time-major activations; label: (N, label_length) with 0 = blank/pad.
    Output: softmax(data); backward = CTC gradient."""
    label_length = int(label_length)
    input_length = int(input_length)
    if input_length <= 0 or label_length <= 0:
        raise MXNetError("WarpCTC requires input_length and label_length")
    if data.ndim != 2:
        raise MXNetError("WarpCTC data must be 2-D (T*N, alphabet)")
    return _warpctc_core(data, label.reshape(-1, label_length),
                         input_length, label_length)


def _ctc_loss_shape(attrs, in_shapes):
    data = in_shapes[0]
    out = (data[1],) if data else None
    return list(in_shapes), [out], []


@register("ctc_loss", input_names=("data", "label"),
          aliases=("_contrib_ctc_loss", "CTCLoss"),
          infer_shape=_ctc_loss_shape)
def ctc_loss_op(data, label):
    """Per-sample CTC negative log likelihood.  data: (T, N, A) time-major
    activations, label: (N, L) with 0 = padding.  Returns (N,) losses.
    Fully differentiable (grad flows to data)."""
    if data.ndim != 3:
        raise MXNetError("ctc_loss data must be 3-D (T, N, alphabet)")
    return ctc_nll(data, label.astype(jnp.int32))
