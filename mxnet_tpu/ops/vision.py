"""Vision ops: ROIPooling, SpatialTransformer, GridGenerator,
BilinearSampler, Correlation.

TPU-native re-design of the reference's CUDA vision layers
(src/operator/roi_pooling.cc, spatial_transformer.cc, bilinear_sampler.cc,
grid_generator.cc, correlation.cc).  Everything is expressed as dense
masked reductions / gathers over static shapes so XLA can tile them; the
gradients fall out of autodiff instead of the reference's hand-written
backward kernels (e.g. ROIPoolBackwardAcc, roi_pooling.cc:133-199).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError


def _pair(x):
    if isinstance(x, (tuple, list)):
        return tuple(int(v) for v in x)
    return (int(x), int(x))


# ---------------------------------------------------------------------------
# ROIPooling — src/operator/roi_pooling-inl.h (pooled_size, spatial_scale)
# ---------------------------------------------------------------------------

def _roi_infer(attrs, in_shapes):
    data, rois = in_shapes[0], in_shapes[1]
    ph, pw = _pair(attrs["pooled_size"])
    if data is None or rois is None:
        return list(in_shapes), [None], []
    out = (rois[0], data[1], ph, pw)
    return [tuple(data), tuple(rois)], [out], []


@register("ROIPooling", input_names=("data", "rois"), infer_shape=_roi_infer)
def roi_pooling(data, rois, pooled_size=None, spatial_scale=1.0):
    """Max-pool regions of interest to a fixed size (reference
    roi_pooling-inl.h ROIPoolForward).  rois are [batch_idx, x1, y1, x2, y2]
    in image coordinates; coordinates are scaled by spatial_scale and
    rounded, matching the reference."""
    ph, pw = _pair(pooled_size)
    n, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        roi_h = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        roi_w = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        img = data[bidx]                              # [C, H, W]

        ys = jnp.arange(h)[None, :]                   # [1, H]
        ih = jnp.arange(ph, dtype=jnp.float32)[:, None]
        hstart = jnp.floor(ih * bin_h).astype(jnp.int32) + y1
        hend = jnp.ceil((ih + 1) * bin_h).astype(jnp.int32) + y1
        hstart = jnp.clip(hstart, 0, h)
        hend = jnp.clip(hend, 0, h)
        mask_h = (ys >= hstart) & (ys < hend)         # [ph, H]

        xs = jnp.arange(w)[None, :]
        iw = jnp.arange(pw, dtype=jnp.float32)[:, None]
        wstart = jnp.floor(iw * bin_w).astype(jnp.int32) + x1
        wend = jnp.ceil((iw + 1) * bin_w).astype(jnp.int32) + x1
        wstart = jnp.clip(wstart, 0, w)
        wend = jnp.clip(wend, 0, w)
        mask_w = (xs >= wstart) & (xs < wend)         # [pw, W]

        neg = jnp.finfo(data.dtype).min
        # max over W per output column: [C, H, pw]
        t = jnp.where(mask_w[None, None, :, :], img[:, :, None, :], neg)
        t = t.max(axis=-1)
        # then max over H per output row: [C, ph, pw]
        o = jnp.where(mask_h[None, :, None, :],
                      jnp.swapaxes(t, 1, 2)[:, None, :, :], neg)
        o = o.max(axis=-1)
        return jnp.where(o == neg, 0.0, o).astype(data.dtype)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# BilinearSampler — src/operator/bilinear_sampler-inl.h
# ---------------------------------------------------------------------------

def _bilinear_sample(data, grid):
    """Sample data [C,H,W] at normalized grid [2,Ho,Wo] ((x,y) in [-1,1]),
    zero padding outside (bilinear_sampler-inl.h between_bounds)."""
    c, h, w = data.shape
    gx = (grid[0] + 1) * (w - 1) / 2
    gy = (grid[1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0
    out = 0.0
    for dy, dx in ((0, 0), (0, 1), (1, 0), (1, 1)):
        xi = x0 + dx
        yi = y0 + dy
        wgt = (wx if dx else (1 - wx)) * (wy if dy else (1 - wy))
        inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        val = data[:, yc, xc]                         # [C, Ho, Wo]
        out = out + jnp.where(inb, wgt, 0.0)[None] * val
    return out


def _bs_infer(attrs, in_shapes):
    data, grid = in_shapes[:2]
    if data is None or grid is None:
        return list(in_shapes), [None], []
    out = (data[0], data[1], grid[2], grid[3])
    return [tuple(data), tuple(grid)], [out], []


@register("BilinearSampler", input_names=("data", "grid"),
          infer_shape=_bs_infer)
def bilinear_sampler(data, grid):
    """data [N,C,H,W], grid [N,2,Ho,Wo] normalized to [-1,1]."""
    return jax.vmap(_bilinear_sample)(data, grid)


# ---------------------------------------------------------------------------
# GridGenerator — src/operator/grid_generator-inl.h
# ---------------------------------------------------------------------------

def _grid_infer(attrs, in_shapes):
    (data,) = in_shapes[:1]
    tt = attrs.get("transform_type", "affine")
    if data is None:
        return list(in_shapes), [None], []
    if tt == "affine":
        th, tw = _pair(attrs["target_shape"])
        return [tuple(data)], [(data[0], 2, th, tw)], []
    return [tuple(data)], [tuple(data)], []


@register("GridGenerator", infer_shape=_grid_infer)
def grid_generator(data, transform_type="affine", target_shape=None):
    """affine: data [N,6] -> sampling grid [N,2,H,W]; warp: data is an
    [N,2,H,W] optical flow added to the identity grid (grid_generator-inl.h)."""
    if transform_type == "affine":
        th, tw = _pair(target_shape)
        ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, th),
                              jnp.linspace(-1, 1, tw), indexing="ij")
        base = jnp.stack([xs.ravel(), ys.ravel(),
                          jnp.ones(th * tw)])        # [3, H*W]
        theta = data.reshape(-1, 2, 3)               # [N, 2, 3]
        grid = jnp.einsum("nij,jk->nik", theta, base)
        return grid.reshape(-1, 2, th, tw)
    if transform_type == "warp":
        n, _two, h, w = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype), indexing="ij")
        gx = (data[:, 0] + xs) * 2 / max(w - 1, 1) - 1
        gy = (data[:, 1] + ys) * 2 / max(h - 1, 1) - 1
        return jnp.stack([gx, gy], axis=1)
    raise MXNetError("unknown transform_type %r" % (transform_type,))


# ---------------------------------------------------------------------------
# SpatialTransformer — src/operator/spatial_transformer-inl.h
# ---------------------------------------------------------------------------

def _st_infer(attrs, in_shapes):
    data, loc = in_shapes[:2]
    th, tw = _pair(attrs["target_shape"])
    if data is None:
        return list(in_shapes), [None], []
    return [tuple(data), (data[0], 6)], [(data[0], data[1], th, tw)], []


@register("SpatialTransformer", input_names=("data", "loc"),
          infer_shape=_st_infer)
def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear"):
    """Affine spatial transformer network layer: loc [N,6] predicts an
    affine transform; output is data sampled on the transformed grid."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine/bilinear only "
                         "(as the reference, spatial_transformer-inl.h)")
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)


# ---------------------------------------------------------------------------
# Correlation — src/operator/correlation-inl.h (FlowNet cost volume)
# ---------------------------------------------------------------------------

def _corr_infer(attrs, in_shapes):
    d1 = in_shapes[0]
    if d1 is None:
        return list(in_shapes), [None], []
    pad = int(attrs.get("pad_size", 0))
    ks = int(attrs.get("kernel_size", 1))
    md = int(attrs.get("max_displacement", 1))
    s1 = int(attrs.get("stride1", 1))
    s2 = int(attrs.get("stride2", 1))
    n, c, h, w = d1
    ph, pw = h + 2 * pad, w + 2 * pad
    kr = ks // 2
    br = (md // s2) * s2 + kr         # border_size
    oh = int(np.ceil(float(ph - br * 2) / s1))
    ow = int(np.ceil(float(pw - br * 2) / s1))
    nd = md // s2 * 2 + 1
    top_c = nd * nd
    return [tuple(d1), tuple(d1)], [(n, top_c, oh, ow)], []


@register("Correlation", input_names=("data1", "data2"),
          infer_shape=_corr_infer)
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Correlation cost volume between two feature maps (correlation-inl.h).
    For each output position and displacement (di,dj), the mean over channels
    and the kernel window of data1*shift(data2) (or |data1-shift(data2)| when
    is_multiply=False)."""
    n, c, h, w = data1.shape
    ks = int(kernel_size)
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    pad = int(pad_size)
    kr = ks // 2
    br = (md // s2) * s2 + kr
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    oh = int(np.ceil(float(ph - br * 2) / s1))
    ow = int(np.ceil(float(pw - br * 2) / s1))
    nd_half = md // s2
    disp = [i * s2 for i in range(-nd_half, nd_half + 1)]
    maps = []
    ys = br + s1 * jnp.arange(oh)
    xs = br + s1 * jnp.arange(ow)
    for dy in disp:
        for dx in disp:
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            if is_multiply:
                prod = p1 * shifted
            else:
                prod = jnp.abs(p1 - shifted)
            if ks > 1:
                prod = lax.reduce_window(
                    prod, 0.0, lax.add, (1, 1, ks, ks), (1, 1, 1, 1),
                    "SAME") / (ks * ks)
            m = prod.mean(axis=1)                     # [N, ph, pw]
            maps.append(m[:, ys][:, :, xs])
    return jnp.stack(maps, axis=1)
