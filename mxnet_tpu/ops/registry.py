"""Operator registry — the single source of truth for all ops.

Re-designs the reference's dual registries (NNVM FCompute ops,
include/mxnet/op_attr_types.h:33-63, and legacy OperatorProperty,
include/mxnet/operator.h:77-155) as ONE registry of pure JAX functions.
Each op is a pure function over jax.Arrays; the imperative layer (ndarray.py)
jit-caches it per attr-set, and the symbolic layer (symbol.py/executor.py)
traces it into a whole-graph jit — which is how the reference's cached-op /
bulk-segment machinery (src/executor/graph_executor.cc:556,690) collapses
into XLA's own fusion.

Op conventions
--------------
``fn(*inputs, **attrs)`` -> jax.Array | tuple of jax.Arrays
  - inputs are the op's data+parameter inputs, in ``input_names`` order,
    followed by aux states in ``aux_names`` order (BatchNorm moving stats —
    the reference's auxiliary states, include/mxnet/operator.h aux_states).
  - if ``needs_is_train``: fn must accept keyword ``is_train`` (bool, static).
  - if ``needs_rng``: fn must accept keyword ``rng`` (jax PRNG key).
  - ops with aux states return outputs + updated aux concatenated in one flat
    tuple; the executor splits on ``num_outputs``.
"""
from __future__ import annotations

import functools

from ..base import MXNetError, parse_attr_value, register_env

ENV_CUSTOM_UNDER_JIT = register_env(
    "MXNET_CUSTOM_UNDER_JIT", default=0,
    doc="1 lets graphs with Custom (host-callback) ops be whole-graph "
        "jitted; default runs them eagerly per-op")

__all__ = ["OpDef", "register", "get_op", "list_ops", "OP_REGISTRY", "apply_op"]

OP_REGISTRY = {}


# attrs the framework itself attaches to nodes (AttrScope / optimizer
# multipliers / graph plumbing) — always allowed alongside op params
FRAMEWORK_ATTRS = frozenset({
    "ctx_group", "lr_mult", "wd_mult", "force_mirroring", "mirror_stage",
    "num_args",
})


@functools.lru_cache(maxsize=2048)
def fn_signature_info(fn):
    """(keyword-accepting param names, has **kwargs) of a lowering fn —
    shared by attr validation here and executor._filter_attrs."""
    import inspect
    params = inspect.signature(fn).parameters
    has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                     for p in params.values())
    names = frozenset(p.name for p in params.values()
                      if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                                    inspect.Parameter.KEYWORD_ONLY))
    return names, has_var_kw


class OpDef(object):
    __slots__ = (
        "name", "fn", "input_names", "aux_names", "num_outputs",
        "infer_shape", "needs_is_train", "needs_rng", "variable_inputs",
        "aliases", "output_names", "hidden", "param_indices", "doc",
        "no_jit", "extra_attrs", "dynamic_attrs", "_accepted",
    )

    def __init__(self, name, fn, input_names=("data",), aux_names=(),
                 num_outputs=1, infer_shape=None, needs_is_train=False,
                 needs_rng=False, variable_inputs=False, aliases=(),
                 output_names=None, hidden=False, no_jit=False,
                 extra_attrs=(), dynamic_attrs=()):
        self.name = name
        self.fn = fn
        self.input_names = input_names          # tuple | callable(attrs)->tuple
        self.aux_names = aux_names              # tuple | callable(attrs)->tuple
        self.num_outputs = num_outputs          # int | callable(attrs)->int
        self.infer_shape = infer_shape          # optional custom shape inference
        self.needs_is_train = needs_is_train
        self.needs_rng = needs_rng
        self.variable_inputs = variable_inputs  # Concat/add_n style variadic
        self.aliases = tuple(aliases)
        self.output_names = output_names        # tuple | callable(attrs)->tuple
        self.hidden = hidden
        self.no_jit = no_jit    # host-callback ops: run eagerly, never jit
        self.extra_attrs = tuple(extra_attrs)  # attrs consumed outside fn
        # scalar attrs passed as TRACED args, not compile-time constants:
        # the imperative jit cache stays one entry per op+shape even when
        # the value changes every call (optimizer lr schedules/bias
        # correction — the reference likewise passes lr at call time,
        # src/operator/optimizer_op-inl.h SGDParam fields are runtime
        # kwargs, not compile specializations)
        self.dynamic_attrs = tuple(dynamic_attrs)
        self._accepted = None   # lazy cache for accepted_attrs()
        self.doc = fn.__doc__

    # -- resolved-per-attrs accessors ------------------------------------
    def get_input_names(self, attrs):
        names = self.input_names
        return tuple(names(attrs)) if callable(names) else tuple(names)

    def get_aux_names(self, attrs):
        names = self.aux_names
        return tuple(names(attrs)) if callable(names) else tuple(names)

    def get_num_outputs(self, attrs):
        n = self.num_outputs
        return n(attrs) if callable(n) else n

    def get_output_names(self, attrs):
        if self.output_names is None:
            n = self.get_num_outputs(attrs)
            if n == 1:
                return ("output",)
            return tuple("output%d" % i for i in range(n))
        names = self.output_names
        return tuple(names(attrs)) if callable(names) else tuple(names)

    def normalize_attrs(self, attrs):
        """Parse string attr values into typed python values."""
        return {k: parse_attr_value(v) for k, v in attrs.items()}

    def accepted_attrs(self):
        """The op's declared parameter surface (the dmlc::Parameter schema
        analog: kwargs of the lowering function plus declared extra_attrs,
        minus tensor inputs/aux and the is_train/rng specials), or None
        when the function takes **kwargs."""
        if self._accepted is None:
            names, has_var_kw = fn_signature_info(self.fn)
            if has_var_kw:
                self._accepted = "any"
            else:
                drop = {"is_train", "rng"}
                try:
                    drop |= set(self.get_input_names({}))
                    drop |= set(self.get_aux_names({}))
                except Exception:  # noqa: BLE001 — attr-dependent callables
                    pass
                self._accepted = frozenset(
                    (names | set(self.extra_attrs)) - drop)
        return None if self._accepted == "any" else self._accepted

    def validate_attrs(self, attrs, where="op call"):
        """Reject unknown parameters instead of silently dropping them —
        dmlc::Parameter semantics (the reference errors on a typo'd
        ``kernal=(3,3)``; src/operator/optimizer_op-inl.h:25-45).
        Framework attrs and ``__dunder__`` user attrs always pass."""
        accepted = self.accepted_attrs()
        if accepted is None:
            return
        bad = [k for k in attrs
               if k not in accepted and k not in FRAMEWORK_ATTRS
               and not (k.startswith("__") and k.endswith("__"))]
        if bad:
            import difflib
            hints = []
            for k in bad:
                close = difflib.get_close_matches(k, sorted(accepted), n=1)
                hints.append("%r%s" % (k, (" (did you mean %r?)" % close[0])
                                       if close else ""))
            raise MXNetError(
                "%s %s: unknown parameter(s) %s; accepted parameters: %s"
                % (self.name, where, ", ".join(hints),
                   ", ".join(sorted(accepted))))

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(name, **kwargs):
    """Decorator registering a JAX function as an op.

    Example::

        @register("broadcast_add", input_names=("lhs", "rhs"),
                  aliases=("broadcast_plus",))
        def broadcast_add(lhs, rhs):
            return jnp.add(lhs, rhs)
    """
    def _reg(fn):
        opdef = OpDef(name, fn, **kwargs)
        if name in OP_REGISTRY:
            raise MXNetError("op %r registered twice" % name)
        OP_REGISTRY[name] = opdef
        for alias in opdef.aliases:
            OP_REGISTRY[alias] = opdef
        return fn
    return _reg


def get_op(name):
    try:
        return OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("operator %r is not registered" % (name,)) from None


def list_ops():
    """Distinct canonical op names (MXListAllOpNames analog)."""
    return sorted({op.name for op in OP_REGISTRY.values()})


# ---------------------------------------------------------------------------
# jit-cached imperative application
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8192)
def _jitted(op_name, attr_items, dyn_names, is_train, with_rng):
    """One compiled callable per (op, static attrs, is_train) — the TPU
    analog of the reference's cached engine ops (graph_executor.cc:556).
    ``dyn_names`` attrs arrive as traced scalars (first positional arg, a
    tuple) so their values don't key the cache."""
    import jax
    op = get_op(op_name)
    attrs = dict(attr_items)
    kw = {}
    if op.needs_is_train:
        kw["is_train"] = is_train

    if with_rng:
        def call(rng, dyn_vals, *arrays):
            return op.fn(*arrays, rng=rng, **attrs,
                         **dict(zip(dyn_names, dyn_vals)), **kw)
    else:
        def call(dyn_vals, *arrays):
            return op.fn(*arrays, **attrs,
                         **dict(zip(dyn_names, dyn_vals)), **kw)
    return jax.jit(call)


@functools.lru_cache(maxsize=1)
def _callback_probe():
    """One-time backend probe: can a pure_callback run under jit here?"""
    import numpy as np
    import jax
    import jax.numpy as jnp
    try:
        f = jax.jit(lambda x: jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct((), jnp.float32), x))
        jax.block_until_ready(f(jnp.float32(0.0)))
        return True
    except Exception:
        return False


def callbacks_under_jit_supported():
    """Whether graphs containing host-callback ops (Custom) may be
    whole-graph jitted.  Default: NO — callbacks then run inside the
    compiled program on a runtime callback thread, and a concurrent
    device_get on the main thread (metric pulls, async dispatch) can
    deadlock against the callback's own host transfers (observed:
    CustomOp inside Module.fit hangs intermittently).  Eager per-op
    execution mirrors the reference, where CustomOp is always a
    host-side engine callback between kernel launches
    (src/operator/custom/custom-inl.h), and makes stateful callback RNG
    deterministic (pure_callback gives no execution-count guarantee).
    Set MXNET_CUSTOM_UNDER_JIT=1 to opt into fused custom-op graphs.
    The env var is read per call (only the backend probe is cached), so
    toggling it mid-process takes effect at the next bind."""
    from ..base import get_env
    if str(get_env(ENV_CUSTOM_UNDER_JIT, "0")) != "1":
        return False
    return _callback_probe()


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def apply_op(op, arrays, attrs, is_train=False, rng=None):
    """Run an op imperatively on jax.Arrays, via the per-attr jit cache.

    Returns a tuple of jax.Arrays (outputs, then updated aux if any).
    """
    op.validate_attrs(attrs, where="imperative call")
    attrs = op.normalize_attrs(attrs)
    accepted = op.accepted_attrs()
    if accepted is not None:
        # framework attrs (ctx_group/lr_mult/...) validated above but not
        # consumed by the lowering fn
        attrs = {k: v for k, v in attrs.items() if k in accepted}
    with_rng = op.needs_rng
    # is_train only keys the cache for ops whose behavior depends on it —
    # otherwise autograd's train-mode default would double-compile every op
    is_train = bool(is_train) and op.needs_is_train
    if op.no_jit:
        kw = {}
        if op.needs_is_train:
            kw["is_train"] = is_train
        if with_rng:
            if rng is None:
                from .. import random as _random
                rng = _random.next_key()
            kw["rng"] = rng
        out = op.fn(*arrays, **attrs, **kw)
        if isinstance(out, (tuple, list)):
            return tuple(out)
        return (out,)
    dyn_names = tuple(k for k in op.dynamic_attrs if k in attrs)
    dyn_vals = tuple(float(attrs[k]) for k in dyn_names)
    items = tuple(sorted((k, _hashable(v)) for k, v in attrs.items()
                         if k not in dyn_names))
    fn = _jitted(op.name, items, dyn_names, is_train, with_rng)
    if with_rng:
        if rng is None:
            from .. import random as _random
            rng = _random.next_key()
        out = fn(rng, dyn_vals, *arrays)
    else:
        out = fn(dyn_vals, *arrays)
    if isinstance(out, (tuple, list)):
        return tuple(out)
    return (out,)
