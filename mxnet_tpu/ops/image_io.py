"""Image I/O ops — the reference's in-engine OpenCV NDArray ops
(reference src/io/image_io.cc:269 registers _cvimdecode/_cvimresize/
_cvcopyMakeBorder; python mx.image rides them).

TPU-first split: `imdecode` is a host op (JPEG entropy decode is inherently
serial — it runs on the native libjpeg decoder, cv2 fallback) marked
no_jit, while `imresize` and `copyMakeBorder` are ordinary XLA lowerings
(jax.image.resize / lax.pad) that run on-device and fuse like any other op.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .registry import register

# cv2 interp codes -> jax.image methods (2=bicubic like the reference's
# OpenCV default; 3=INTER_AREA has no jax analog, mapped to linear)
_INTERP = {0: "nearest", 1: "linear", 2: "cubic", 3: "linear", 4: "lanczos3"}


def _decode_host(buf, flag, to_rgb):
    """bytes -> HWC uint8 numpy (BGR when to_rgb=0, reference default)."""
    from .. import native as _native

    raw = bytes(buf)
    lib = _native.get_lib()
    # the native JPEG path always yields 3 channels; flags other than
    # 0 (gray) and 1 (color) — e.g. IMREAD_UNCHANGED=-1, which must return
    # 2-D for grayscale sources like the reference _cvimdecode — go to cv2
    if (lib is not None and getattr(lib, "_has_imagedec", False)
            and int(flag) in (0, 1)):
        import ctypes as ct
        h = ct.c_int()
        w = ct.c_int()
        cbuf = ct.cast(ct.c_char_p(raw), ct.c_void_p)
        if lib.MXTPUImgDecodeDims(cbuf, len(raw), ct.byref(h),
                                  ct.byref(w)) == 0:
            out = np.empty((h.value, w.value, 3), np.uint8)
            if lib.MXTPUImgDecode(cbuf, len(raw), out.ctypes.data_as(
                    ct.c_void_p), 1 if to_rgb else 0) == 0:
                if flag == 0:  # grayscale requested
                    coef = (np.array([0.299, 0.587, 0.114])
                            if to_rgb else np.array([0.114, 0.587, 0.299]))
                    g = (out.astype(np.float32) * coef).sum(-1)
                    return np.clip(g + 0.5, 0,
                                   255).astype(np.uint8)[:, :, None]
                return out
        # non-JPEG payloads (png, ...) fall through to cv2
    import cv2
    img = cv2.imdecode(np.frombuffer(raw, np.uint8), int(flag))
    if img is None:
        raise MXNetError("imdecode: cannot decode image")
    if img.ndim == 2:
        img = img[:, :, None]
    elif to_rgb:
        img = np.ascontiguousarray(img[..., ::-1])
    return img


@register("imdecode", input_names=("buf",), aliases=("_cvimdecode",),
          no_jit=True)
def imdecode_op(buf, flag=1, to_rgb=1):
    """Decode an image byte buffer into an HWC uint8 array (reference
    src/io/image_io.cc Imdecode; _cvimdecode defaults: flag=1 color,
    to_rgb=1).  Host op: output shape depends on the image content, so it
    is imperative-only (the reference likewise executes it eagerly on the
    engine's CPU queue)."""
    import jax.numpy as jnp
    host = np.asarray(buf)
    if host.dtype != np.uint8 or host.ndim != 1:
        raise MXNetError("imdecode expects a 1-D uint8 buffer NDArray")
    return jnp.asarray(_decode_host(host.tobytes(), int(flag), int(to_rgb)))


@register("imresize", input_names=("src",), aliases=("_cvimresize",))
def imresize_op(src, w=0, h=0, interp=1):
    """Resize HWC image to (h, w) — reference _cvimresize, as an XLA
    lowering (jax.image.resize) so it runs on-device."""
    import jax.image
    import jax.numpy as jnp
    method = _INTERP.get(int(interp), "linear")
    out_shape = (int(h), int(w)) + tuple(src.shape[2:])
    out = jax.image.resize(src.astype(jnp.float32), out_shape, method=method)
    if src.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255)
    return out.astype(src.dtype)


@register("copyMakeBorder", input_names=("src",),
          aliases=("_cvcopyMakeBorder",))
def copy_make_border_op(src, top=0, bot=0, left=0, right=0, type=0,
                        value=0.0):
    """Pad an HWC image with a constant border — reference
    _cvcopyMakeBorder (only BORDER_CONSTANT, type=0, like the reference's
    default use in mx.image)."""
    import jax.numpy as jnp
    if int(type) != 0:
        raise MXNetError("copyMakeBorder: only type=0 (constant) supported")
    pads = [(int(top), int(bot)), (int(left), int(right))] + \
        [(0, 0)] * (src.ndim - 2)
    return jnp.pad(src, pads, constant_values=jnp.asarray(
        value, dtype=src.dtype))
