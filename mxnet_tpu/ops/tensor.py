"""Tensor ops — the NNVM FCompute op surface as pure JAX lowerings.

Covers the reference's ``src/operator/tensor/`` families (9,672 LoC of
CUDA/mshadow there; here each op is a few lines of jax/lax that XLA fuses and
tiles onto the MXU/VPU):
- elemwise unary/binary + scalar + broadcast + logic (elemwise_*op*.cc)
- reductions (broadcast_reduce_op_value.cc)
- matrix ops: dot/batch_dot/transpose/reshape/slice/... (matrix_op.cc)
- init ops (init_op.cc), indexing ops (indexing_op.cc),
  ordering ops (ordering_op.cc), control flow (control_flow_op.cc),
  sampling (sample_op.cc), optimizer update ops (optimizer_op.cc:18-73)

Reshape implements the reference's special codes 0/-1/-2/-3/-4
(src/operator/tensor/matrix_op-inl.h ReshapeParam).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _tuple(x):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


def _norm_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(a + ndim if a < 0 else a for a in axis)
    return axis + ndim if axis < 0 else axis


def _reduce_axes(data, axis, exclude=False):
    if axis is None or axis == () or axis == []:
        axes = tuple(range(data.ndim))
    else:
        axes = _norm_axis(_tuple(axis), data.ndim)
    if exclude:
        axes = tuple(i for i in range(data.ndim) if i not in axes)
    return axes


# ---------------------------------------------------------------------------
# elemwise binary (same-shape) — elemwise_binary_op.cc
# ---------------------------------------------------------------------------

@register("elemwise_add", input_names=("lhs", "rhs"), aliases=("_add", "_plus", "_Plus"))
def elemwise_add(lhs, rhs):
    return jnp.add(lhs, rhs)


@register("elemwise_sub", input_names=("lhs", "rhs"), aliases=("_sub", "_minus", "_Minus"))
def elemwise_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("elemwise_mul", input_names=("lhs", "rhs"), aliases=("_mul", "_Mul"))
def elemwise_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("elemwise_div", input_names=("lhs", "rhs"), aliases=("_div", "_Div"))
def elemwise_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("_power", input_names=("lhs", "rhs"), aliases=("_Power",))
def _power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("_maximum", input_names=("lhs", "rhs"), aliases=("_Maximum",))
def _maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("_minimum", input_names=("lhs", "rhs"), aliases=("_Minimum",))
def _minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("_hypot", input_names=("lhs", "rhs"), aliases=("_Hypot",))
def _hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@register("_grad_add", input_names=("lhs", "rhs"))
def _grad_add(lhs, rhs):
    """Gradient aggregation add (reference src/executor/graph_executor.cc:90)."""
    return jnp.add(lhs, rhs)


# ---------------------------------------------------------------------------
# broadcast binary — elemwise_binary_broadcast_op.cc
# ---------------------------------------------------------------------------

def _broadcast_binary(name, jfn, aliases=()):
    @register(name, input_names=("lhs", "rhs"), aliases=aliases)
    def _op(lhs, rhs, _jfn=jfn):
        return _jfn(lhs, rhs)
    _op.__name__ = name
    return _op


_broadcast_binary("broadcast_add", jnp.add, aliases=("broadcast_plus",))
_broadcast_binary("broadcast_sub", jnp.subtract, aliases=("broadcast_minus",))
_broadcast_binary("broadcast_mul", jnp.multiply)
_broadcast_binary("broadcast_div", jnp.divide)
_broadcast_binary("broadcast_mod", jnp.mod)
_broadcast_binary("broadcast_power", jnp.power)
_broadcast_binary("broadcast_maximum", jnp.maximum)
_broadcast_binary("broadcast_minimum", jnp.minimum)
_broadcast_binary("broadcast_hypot", jnp.hypot)


def _logic(name, jfn, aliases=()):
    @register(name, input_names=("lhs", "rhs"), aliases=aliases)
    def _op(lhs, rhs, _jfn=jfn):
        return _jfn(lhs, rhs).astype(jnp.result_type(lhs))
    return _op


_logic("broadcast_equal", jnp.equal, aliases=("_equal", "_Equal"))
_logic("broadcast_not_equal", jnp.not_equal, aliases=("_not_equal", "_Not_Equal"))
_logic("broadcast_greater", jnp.greater, aliases=("_greater", "_Greater"))
_logic("broadcast_greater_equal", jnp.greater_equal,
       aliases=("_greater_equal", "_Greater_Equal"))
_logic("broadcast_lesser", jnp.less, aliases=("_lesser", "_Lesser"))
_logic("broadcast_lesser_equal", jnp.less_equal,
       aliases=("_lesser_equal", "_Lesser_Equal"))
_logic("broadcast_logical_and", jnp.logical_and)
_logic("broadcast_logical_or", jnp.logical_or)
_logic("broadcast_logical_xor", jnp.logical_xor)


# ---------------------------------------------------------------------------
# scalar ops — elemwise_binary_scalar_op.cc
# ---------------------------------------------------------------------------

def _scalar_op(name, fn, aliases=()):
    @register(name, aliases=aliases)
    def _op(data, scalar=0.0, _fn=fn):
        return _fn(data, jnp.asarray(scalar, dtype=data.dtype))
    return _op


_scalar_op("_plus_scalar", lambda a, s: a + s, aliases=("_PlusScalar",))
_scalar_op("_minus_scalar", lambda a, s: a - s, aliases=("_MinusScalar",))
_scalar_op("_rminus_scalar", lambda a, s: s - a, aliases=("_RMinusScalar",))
_scalar_op("_mul_scalar", lambda a, s: a * s, aliases=("_MulScalar",))
_scalar_op("_div_scalar", lambda a, s: a / s, aliases=("_DivScalar",))
_scalar_op("_rdiv_scalar", lambda a, s: s / a, aliases=("_RDivScalar",))
_scalar_op("_mod_scalar", lambda a, s: jnp.mod(a, s))
_scalar_op("_rmod_scalar", lambda a, s: jnp.mod(s, a))
_scalar_op("_power_scalar", jnp.power, aliases=("_PowerScalar",))
_scalar_op("_rpower_scalar", lambda a, s: jnp.power(s, a), aliases=("_RPowerScalar",))
_scalar_op("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_scalar_op("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))
_scalar_op("_hypot_scalar", jnp.hypot, aliases=("_HypotScalar",))
_scalar_op("_equal_scalar", lambda a, s: (a == s).astype(a.dtype),
           aliases=("_EqualScalar",))
_scalar_op("_not_equal_scalar", lambda a, s: (a != s).astype(a.dtype),
           aliases=("_NotEqualScalar",))
_scalar_op("_greater_scalar", lambda a, s: (a > s).astype(a.dtype),
           aliases=("_GreaterScalar",))
_scalar_op("_greater_equal_scalar", lambda a, s: (a >= s).astype(a.dtype),
           aliases=("_GreaterEqualScalar",))
_scalar_op("_lesser_scalar", lambda a, s: (a < s).astype(a.dtype),
           aliases=("_LesserScalar",))
_scalar_op("_lesser_equal_scalar", lambda a, s: (a <= s).astype(a.dtype),
           aliases=("_LesserEqualScalar",))


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    """Smooth L1 (reference src/operator/tensor/elemwise_binary_scalar_op_extended.cc)."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data, absd - 0.5 / s2)


# ---------------------------------------------------------------------------
# unary — elemwise_unary_op.cc, mshadow_op.h kernels
# ---------------------------------------------------------------------------

def _unary(name, jfn, aliases=()):
    @register(name, aliases=aliases)
    def _op(data, _jfn=jfn):
        return _jfn(data)
    return _op


_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lax.rsqrt)
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("negative", jnp.negative)
_unary("reciprocal", jnp.reciprocal)
_unary("gamma", lambda x: jnp.exp(lax.lgamma(x)))
_unary("gammaln", lax.lgamma)
_unary("erf", lax.erf)
_unary("erfinv", lax.erf_inv)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))
_unary("identity", lambda x: x, aliases=("_copy", "_identity_with_attr_like_rhs"))


@register("BlockGrad", aliases=("stop_gradient", "block_grad"))
def block_grad(data):
    """Forward identity, zero gradient (reference src/operator/block_grad.cc)."""
    return lax.stop_gradient(data)


@register("Cast", aliases=("cast",))
def cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("softmax", aliases=("Softmax",))
def softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


# ---------------------------------------------------------------------------
# reductions — broadcast_reduce_op_value.cc
# ---------------------------------------------------------------------------

def _reduce(name, jfn, aliases=(), dtype_keep=True):
    @register(name, aliases=aliases)
    def _op(data, axis=None, keepdims=False, exclude=False, _jfn=jfn):
        axes = _reduce_axes(data, axis, exclude)
        return _jfn(data, axis=axes, keepdims=bool(keepdims))
    return _op


_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max, aliases=("max_axis",))
_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    axes = _reduce_axes(data, axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axes, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=bool(keepdims)))


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=_norm_axis(axis, data.ndim), keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=_norm_axis(axis, data.ndim), keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    """argmax over axis 1 (reference broadcast_reduce_op_value.cc argmax_channel)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# broadcast_to / broadcast_axis
# ---------------------------------------------------------------------------

@register("broadcast_to")
def broadcast_to(data, shape=()):
    shape = _tuple(shape)
    target = tuple(d if s == 0 else s for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, target)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axis, size = _tuple(axis), _tuple(size)
    target = list(data.shape)
    for a, s in zip(axis, size):
        target[_norm_axis(a, data.ndim)] = s
    return jnp.broadcast_to(data, tuple(target))


# ---------------------------------------------------------------------------
# matrix ops — matrix_op.cc
# ---------------------------------------------------------------------------

@register("dot", input_names=("lhs", "rhs"))
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """dot (reference src/operator/tensor/matrix_op.cc dot) — lowers straight
    onto the MXU via lax.dot_general after flattening to 2D semantics."""
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot", input_names=("lhs", "rhs"))
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("transpose")
def transpose(data, axes=()):
    axes = _tuple(axes)
    if not axes:
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("SwapAxis", aliases=("swapaxes", "SwapAxes"))
def swapaxes(data, dim1=0, dim2=0):
    """reference src/operator/swapaxis.cc"""
    return jnp.swapaxes(data, dim1, dim2)


def infer_reshape(in_shape, target, reverse=False):
    """Implements the reference ReshapeParam special codes
    (matrix_op-inl.h): 0 copy, -1 infer, -2 copy-all-remaining,
    -3 merge-two, -4 split-one."""
    in_shape = list(in_shape)
    if reverse:
        in_shape = in_shape[::-1]
        target = list(target)[::-1]
    out = []
    src_idx = 0
    infer_idx = -1
    i = 0
    target = list(target)
    while i < len(target):
        t = target[i]
        if t == 0:
            out.append(in_shape[src_idx]); src_idx += 1
        elif t == -1:
            infer_idx = len(out); out.append(1)
        elif t == -2:
            out.extend(in_shape[src_idx:]); src_idx = len(in_shape)
        elif t == -3:
            out.append(in_shape[src_idx] * in_shape[src_idx + 1]); src_idx += 2
        elif t == -4:
            d1, d2 = target[i + 1], target[i + 2]
            src = in_shape[src_idx]; src_idx += 1
            if d1 == -1:
                d1 = src // d2
            if d2 == -1:
                d2 = src // d1
            out.extend([d1, d2]); i += 2
        else:
            out.append(t)
            if t != -1:
                src_idx += 1 if src_idx < len(in_shape) else 0
        i += 1
    total = 1
    for d in in_shape:
        total *= d
    if infer_idx >= 0:
        known = 1
        for j, d in enumerate(out):
            if j != infer_idx:
                known *= d
        out[infer_idx] = total // max(known, 1)
    if reverse:
        out = out[::-1]
    return tuple(out)


@register("Reshape", aliases=("reshape",))
def reshape(data, shape=(), reverse=False, target_shape=None, keep_highest=False):
    if target_shape:  # legacy attr (matrix_op-inl.h:144-161): exactly one
        # 0 entry is INFERRED from the rest (unlike new-style shape,
        # where 0 copies the input dim); keep_highest pins dim0
        tgt = list(_tuple(target_shape))
        start = 0
        if keep_highest:
            tgt[0] = data.shape[0]
            start = 1
        zeros = [i for i in range(start, len(tgt)) if tgt[i] == 0]
        if len(zeros) == 1:
            tgt[zeros[0]] = -1
        return jnp.reshape(data, tuple(tgt))
    return jnp.reshape(data, infer_reshape(data.shape, _tuple(shape), reverse))


@register("Flatten", aliases=("flatten",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    begin, end = _tuple(begin), _tuple(end)
    step = _tuple(step) if step else (1,) * len(begin)
    idx = []
    for i in range(data.ndim):
        if i < len(begin):
            b = begin[i]
            e = end[i] if i < len(end) else None
            s = step[i] if i < len(step) and step[i] else 1
            idx.append(slice(b if b is not None else None,
                             e if e is not None else None, s))
        else:
            idx.append(slice(None))
    return data[tuple(idx)]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    axis = _norm_axis(axis, data.ndim)
    n = data.shape[axis]
    if end is None:
        end = n
    if end < 0:
        end += n
    if begin < 0:
        begin += n
    return lax.slice_in_dim(data, begin, end, axis=axis)


@register("reverse", aliases=("flip",))
def reverse(data, axis=()):
    return jnp.flip(data, axis=_norm_axis(_tuple(axis), data.ndim))


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=_norm_axis(axis, data.ndim))


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, _tuple(reps))


@register("stack", variable_inputs=True, input_names=lambda attrs: tuple(
    "arg%d" % i for i in range(int(attrs.get("num_args", 1)))))
def stack(*args, num_args=1, axis=0):
    return jnp.stack(args, axis=axis)


@register("add_n", variable_inputs=True, aliases=("ElementWiseSum", "_sum"),
          input_names=lambda attrs: tuple(
              "arg%d" % i for i in range(int(attrs.get("num_args", 1)))))
def add_n(*args, num_args=None):
    """reference src/operator/tensor/elemwise_sum.cc"""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ---------------------------------------------------------------------------
# init ops — init_op.cc
# ---------------------------------------------------------------------------

@register("_zeros", input_names=(), aliases=("zeros",))
def _zeros(shape=(), dtype="float32", ctx=None):
    return jnp.zeros(_tuple(shape), dtype=jnp.dtype(dtype))


@register("_ones", input_names=(), aliases=("ones",))
def _ones(shape=(), dtype="float32", ctx=None):
    return jnp.ones(_tuple(shape), dtype=jnp.dtype(dtype))


@register("_full", input_names=(), aliases=("full",))
def _full(shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(_tuple(shape), value, dtype=jnp.dtype(dtype))


@register("_arange", input_names=(), aliases=("arange",))
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


# ---------------------------------------------------------------------------
# indexing — indexing_op.cc
# ---------------------------------------------------------------------------

@register("take", input_names=("a", "indices"))
def take(a, indices, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@register("batch_take", input_names=("a", "indices"))
def batch_take(a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("one_hot", input_names=("indices",))
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * on_value + (1.0 - oh) * off_value


@register("_onehot_encode", input_names=("index", "out"),
          aliases=("onehot_encode",))
def _onehot_encode(index, out):
    """One-hot encode ``index`` into the shape/dtype of ``out`` (the
    reference's in-place _onehot_encode, src/ndarray/ndarray.cc:751,
    ndarray_function-inl.h:64)."""
    return jax.nn.one_hot(index.astype(jnp.int32), out.shape[1],
                          dtype=out.dtype)


@register("pick", input_names=("data", "index"))
def pick(data, index, axis=1, keepdims=False):
    axis = _norm_axis(axis, data.ndim)
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("choose_element_0index", input_names=("lhs", "rhs"))
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] — the reference's MatChooseRowElem
    (src/ndarray/ndarray.cc:755, ndarray_function-inl.h:84)."""
    idx = rhs.astype(jnp.int32)[:, None]
    return jnp.take_along_axis(lhs, idx, axis=1)[:, 0]


@register("fill_element_0index", input_names=("lhs", "mhs", "rhs"))
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] — the reference's
    MatFillRowElem (src/ndarray/ndarray.cc:761, ndarray_function-inl.h:101)."""
    idx = rhs.astype(jnp.int32)[:, None]
    return jnp.put_along_axis(lhs, idx, mhs[:, None], axis=1,
                              inplace=False)


# ---------------------------------------------------------------------------
# control flow — control_flow_op.cc
# ---------------------------------------------------------------------------

@register("where", input_names=("condition", "x", "y"))
def where(condition, x, y):
    if condition.ndim == 1 and x.ndim > 1:
        shape = (-1,) + (1,) * (x.ndim - 1)
        condition = condition.reshape(shape)
    return jnp.where(condition != 0, x, y)


# ---------------------------------------------------------------------------
# ordering — ordering_op.cc
# ---------------------------------------------------------------------------

@register("topk", num_outputs=lambda attrs: 2 if str(attrs.get("ret_typ", "indices")) == "both" else 1)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    axis = _norm_axis(axis, data.ndim)
    src = jnp.swapaxes(data, axis, -1)
    neg = src if not is_ascend else -src
    vals, idxs = lax.top_k(neg, k)
    if is_ascend:
        vals = -vals
    vals = jnp.swapaxes(vals, axis, -1)
    idxs = jnp.swapaxes(idxs, axis, -1).astype(jnp.float32)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    if ret_typ == "mask":
        mask = jnp.zeros(src.shape, dtype=data.dtype)
        mask = jnp.put_along_axis(mask, idxs.astype(jnp.int32), 1.0, axis=-1,
                                  inplace=False)
        return jnp.swapaxes(mask, axis, -1)
    return idxs


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=_norm_axis(axis, data.ndim))
    if not is_ascend:
        out = jnp.flip(out, axis=_norm_axis(axis, data.ndim))
    return out


@register("argsort")
def argsort(data, axis=-1, is_ascend=True):
    axis = _norm_axis(axis, data.ndim)
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


# ---------------------------------------------------------------------------
# sampling — sample_op.cc
# ---------------------------------------------------------------------------

@register("_sample_uniform", input_names=(), needs_rng=True,
          aliases=("uniform", "_random_uniform", "random_uniform"))
def _sample_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.uniform(rng, _tuple(shape), dtype=jnp.dtype(dtype),
                              minval=low, maxval=high)


@register("_sample_normal", input_names=(), needs_rng=True,
          aliases=("normal", "_random_normal", "random_normal"))
def _sample_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return loc + scale * jax.random.normal(rng, _tuple(shape), dtype=jnp.dtype(dtype))


@register("_sample_gamma", input_names=(), needs_rng=True, aliases=("gamma_sample",))
def _sample_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.gamma(rng, alpha, _tuple(shape), dtype=jnp.dtype(dtype)) * beta


# ---------------------------------------------------------------------------
# optimizer update ops — optimizer_op.cc:18-73 (the dist-server update path)
# ---------------------------------------------------------------------------

def _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad + wd * weight


@register("sgd_update", input_names=("weight", "grad"),
          dynamic_attrs=("lr", "wd", "rescale_grad"))
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd)
    return weight - lr * g


@register("sgd_mom_update", input_names=("weight", "grad", "mom"),
          num_outputs=2, dynamic_attrs=("lr", "wd", "rescale_grad"))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd)
    mom = momentum * mom - lr * g
    return weight + mom, mom


@register("adam_update", input_names=("weight", "grad", "mean", "var"),
          num_outputs=3, dynamic_attrs=("lr", "wd", "rescale_grad"))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd)
    mean = beta1 * mean + (1.0 - beta1) * g
    var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    weight = weight - lr * mean / (jnp.sqrt(var) + epsilon)
    return weight, mean, var


@register("rmsprop_update", input_names=("weight", "grad", "n"),
          num_outputs=2, dynamic_attrs=("lr", "wd", "rescale_grad"))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd)
    n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    weight = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n


@register("rmspropalex_update", input_names=("weight", "grad", "n", "g", "delta"),
          num_outputs=4, dynamic_attrs=("lr", "wd", "rescale_grad"))
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _apply_wd_clip(weight, grad, rescale_grad, clip_gradient, wd)
    n = gamma1 * n + (1.0 - gamma1) * jnp.square(gr)
    g = gamma1 * g + (1.0 - gamma1) * gr
    delta = gamma2 * delta - lr * gr / jnp.sqrt(n - jnp.square(g) + epsilon)
    weight = weight + delta
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n, g, delta


# ---------------------------------------------------------------------------
# loss helpers — loss_binary_op.cc
# ---------------------------------------------------------------------------

@register("softmax_cross_entropy", input_names=("data", "label"))
def softmax_cross_entropy(data, label):
    """reference src/operator/loss_binary_op.cc — summed cross entropy."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


@register("_CrossDeviceCopy", hidden=True)
def _cross_device_copy(data):
    """Placement-boundary copy node (reference cross_device_copy.cc,
    inserted by the PlaceDevice pass).  Under group2ctx the executor's
    device_put at boundaries performs the transfer; graphs serialized by
    the reference load and run with this as identity."""
    return data


def _slice_like_infer(attrs, in_shapes):
    lhs = in_shapes[0]
    if lhs is None:
        return list(in_shapes), [None], []
    new_in = [tuple(s) if s is not None else None for s in in_shapes]
    if len(in_shapes) > 1 and in_shapes[1] is None:
        # infer rhs as the sliced extent (reference SliceAssignOpShape)
        begin = attrs.get("begin", ())
        end = attrs.get("end", ())
        step = attrs.get("step", ()) or (None,) * len(begin)
        rhs = list(lhs)
        for ax, (b, e, st) in enumerate(zip(begin, end, step)):
            sl = slice(b, e, st)
            start, stop, stride = sl.indices(lhs[ax])
            rhs[ax] = max(0, -(-(stop - start) // stride))
        new_in[1] = tuple(rhs)
    return new_in, [tuple(lhs)], []


@register("_slice_assign", input_names=("lhs", "rhs"),
          aliases=("_crop_assign",), infer_shape=_slice_like_infer,
          hidden=True)
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """lhs with lhs[begin:end] replaced by rhs (reference matrix_op
    _slice_assign / _crop_assign — the graph form of x[a:b] = y).
    begin/end entries may be None (full extent), like the slice op."""
    def _i(v):
        return None if v is None else int(v)
    idx = tuple(slice(_i(b), _i(e), _i(s) if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return lhs.at[idx].set(rhs)


@register("_crop_assign_scalar", infer_shape=_slice_like_infer, hidden=True)
def _crop_assign_scalar(data, scalar=0.0, begin=(), end=()):
    """data with data[begin:end] = scalar (reference matrix_op
    _crop_assign_scalar — the graph form of x[a:b] = c); None = full
    extent."""
    idx = tuple(slice(None if b is None else int(b),
                      None if e is None else int(e))
                for b, e in zip(begin, end))
    return data.at[idx].set(scalar)
