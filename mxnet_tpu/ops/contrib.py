"""contrib ops: SSD MultiBox family, Faster R-CNN Proposal, FFT/IFFT,
count_sketch (reference src/operator/contrib/, 4.4k LoC CUDA/C++).

TPU-native re-design: anchor generation / target matching / NMS are dense
fixed-shape computations (masking instead of dynamic lists) so they stay
inside XLA programs; the reference's CUDA NMS loops become a
``lax.fori_loop`` over score-sorted candidates with a suppression mask.
Detection-style outputs are gradient-free (wrapped in stop_gradient), like
the reference layers that declare no backward.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError


def _flist(v, default):
    if v is None:
        return tuple(default)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


# ---------------------------------------------------------------------------
# MultiBoxPrior — contrib/multibox_prior-inl.h
# ---------------------------------------------------------------------------

def _mbp_infer(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return list(in_shapes), [None], []
    sizes = _flist(attrs.get("sizes"), (1.0,))
    ratios = _flist(attrs.get("ratios"), (1.0,))
    na = len(sizes) + len(ratios) - 1
    h, w = data[2], data[3]
    return [tuple(data)], [(1, h * w * na, 4)], []


@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",),
          infer_shape=_mbp_infer)
def multibox_prior(data, sizes=None, ratios=None, clip=False, steps=None,
                   offsets=None):
    """Generate SSD prior (anchor) boxes for each feature-map cell
    (multibox_prior-inl.h MultiBoxPriorForward).  Output (1, H*W*A, 4) with
    corners (x1,y1,x2,y2) normalized to [0,1]."""
    sizes = _flist(sizes, (1.0,))
    ratios = _flist(ratios, (1.0,))
    offsets = _flist(offsets, (0.5, 0.5))
    h, w = data.shape[2], data.shape[3]
    steps = _flist(steps, (-1.0, -1.0))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w

    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")     # [h, w]

    # anchors: square (s, s) boxes for every size (the reference's
    # multibox_prior.cc uses w=h=size/2 half-extents for all size anchors,
    # ignoring ratios), then size[0] stretched by sqrt(ratio) for ratios[1:]
    whs = [(s, s) for s in sizes]
    whs += [(sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r))
            for r in ratios[1:]]
    boxes = []
    for bw, bh in whs:
        x1 = cxg - bw / 2
        y1 = cyg - bh / 2
        x2 = cxg + bw / 2
        y2 = cyg + bh / 2
        boxes.append(jnp.stack([x1, y1, x2, y2], axis=-1))  # [h, w, 4]
    out = jnp.stack(boxes, axis=2).reshape(1, -1, 4)        # [1, h*w*A, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return lax.stop_gradient(out)


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------

def _iou(a, b):
    """IoU between [A,4] and [B,4] corner boxes -> [A,B]."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[None, :, 0], b[None, :, 1], b[None, :, 2], \
        b[None, :, 3]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0) * jnp.maximum(ay2 - ay1, 0)
    area_b = jnp.maximum(bx2 - bx1, 0) * jnp.maximum(by2 - by1, 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def _encode(anchors, gt, variances):
    """Corner gt vs corner anchors -> center-form regression targets
    (multibox_target-inl.h encoding)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    tx = (gcx - acx) / jnp.maximum(aw, 1e-12) / variances[0]
    ty = (gcy - acy) / jnp.maximum(ah, 1e-12) / variances[1]
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-12), 1e-12)) / \
        variances[2]
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-12), 1e-12)) / \
        variances[3]
    return jnp.stack([tx, ty, tw, th], axis=-1)


def _decode(anchors, deltas, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = deltas[:, 0] * variances[0] * aw + acx
    cy = deltas[:, 1] * variances[1] * ah + acy
    w = jnp.exp(deltas[:, 2] * variances[2]) * aw
    h = jnp.exp(deltas[:, 3] * variances[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


# ---------------------------------------------------------------------------
# MultiBoxTarget — contrib/multibox_target-inl.h
# ---------------------------------------------------------------------------

def _mbt_infer(attrs, in_shapes):
    anchor, label, cls_pred = in_shapes[:3]
    if anchor is None or label is None or cls_pred is None:
        return list(in_shapes), [None, None, None], []
    a = anchor[1]
    n = label[0]
    return ([tuple(anchor), tuple(label), tuple(cls_pred)],
            [(n, a * 4), (n, a * 4), (n, a)], [])


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          input_names=("anchor", "label", "cls_pred"), num_outputs=3,
          output_names=("loc_target", "loc_mask", "cls_target"),
          infer_shape=_mbt_infer)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training-target assignment (multibox_target-inl.h): match each
    anchor to ground truth (best-anchor-per-gt plus IoU>threshold), emit
    localization targets/masks and classification targets."""
    variances = _flist(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor[0]                                # [A, 4]
    a = anchors.shape[0]

    mine = float(negative_mining_ratio) > 0

    def per_sample(lbl, pred):
        # lbl: [O, 5] rows (cls, x1, y1, x2, y2), cls<0 = padding
        valid = lbl[:, 0] >= 0                         # [O]
        gt = lbl[:, 1:5]
        iou = _iou(anchors, gt)                        # [A, O]
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)              # [A]
        best_iou = jnp.max(iou, axis=1)
        # force-match: iterative bipartite matching, one distinct anchor
        # per valid gt (multibox_target-inl.h greedy matching): each round
        # takes the globally-best remaining (anchor, gt) pair, then masks
        # that anchor row and gt column so no anchor or gt matches twice.
        n_gt = gt.shape[0]

        def match_round(_, state):
            iou_m, forced, forced_gt = state
            flat = iou_m.reshape(-1)
            idx = jnp.argmax(flat)
            ai = idx // n_gt
            gi = (idx % n_gt).astype(jnp.int32)
            ok = flat[idx] >= 0.0          # invalid/exhausted entries < 0
            forced = forced.at[ai].set(forced[ai] | ok)
            forced_gt = forced_gt.at[ai].set(
                jnp.where(ok, gi, forced_gt[ai]))
            iou_m = iou_m.at[ai, :].set(-2.0)
            iou_m = iou_m.at[:, gi].set(-2.0)
            return iou_m, forced, forced_gt

        _, forced, forced_gt = lax.fori_loop(
            0, n_gt, match_round,
            (iou, jnp.zeros((a,), bool), jnp.zeros((a,), jnp.int32)))
        pos = forced | (best_iou >= overlap_threshold)
        match = jnp.where(forced, forced_gt, best_gt)
        matched_gt = gt[match]                         # [A, 4]
        loc_t = _encode(anchors, matched_gt, variances)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.broadcast_to(pos[:, None],
                                 (a, 4)).astype(jnp.float32).reshape(-1)
        cls_t = jnp.where(pos, lbl[match, 0] + 1, 0.0)  # 0 = background
        if mine:
            # hard negative mining (multibox_target-inl.h NegativeMining):
            # candidates = anchors below the mining IoU threshold, ranked by
            # background cross-entropy (-log p_bg from cls_pred softmax);
            # keep ratio*num_pos (>= minimum_negative_samples), rest ignored
            p = jax.nn.softmax(pred, axis=0)           # [cls, A]
            neg_score = -jnp.log(jnp.maximum(p[0], 1e-12))
            cand = (~pos) & (best_iou < negative_mining_thresh)
            num_pos = pos.sum()
            num_neg = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                int(minimum_negative_samples))
            score = jnp.where(cand, neg_score, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.argsort(order)
            selected = cand & (rank < num_neg)
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(selected, 0.0, ignore_label))
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label, cls_pred)
    return (lax.stop_gradient(loc_t), lax.stop_gradient(loc_m),
            lax.stop_gradient(cls_t))


# ---------------------------------------------------------------------------
# MultiBoxDetection — contrib/multibox_detection-inl.h
# ---------------------------------------------------------------------------

def _mbd_infer(attrs, in_shapes):
    cls_prob, loc_pred, anchor = in_shapes[:3]
    if cls_prob is None or anchor is None:
        return list(in_shapes), [None], []
    return ([tuple(cls_prob), tuple(loc_pred), tuple(anchor)],
            [(cls_prob[0], anchor[1], 6)], [])


def _nms_mask(boxes, scores, valid, nms_threshold, topk):
    """Greedy NMS via fori_loop over the topk score-sorted candidates;
    returns keep mask [A]."""
    order = jnp.argsort(-scores)
    keep = valid

    rank = jnp.argsort(order)                          # score rank per box

    def body(i, keep):
        idx = order[i]
        alive = keep[idx]
        ious = _iou(boxes[idx][None, :], boxes)[0]     # [A]
        # suppress strictly-lower-ranked boxes overlapping idx
        suppress = (ious > nms_threshold) & (rank > rank[idx])
        return jnp.where(alive, keep & ~suppress, keep)

    return lax.fori_loop(0, topk, body, keep)


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
          input_names=("cls_prob", "loc_pred", "anchor"),
          infer_shape=_mbd_infer)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1):
    """SSD detection output (multibox_detection-inl.h): decode loc
    predictions against anchors, take per-anchor best non-background class,
    score-threshold, per-class greedy NMS.  Output [N, A, 6] rows
    (class_id, score, x1, y1, x2, y2); suppressed rows have class_id=-1."""
    variances = _flist(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor[0]
    a = anchors.shape[0]
    topk = a if nms_topk is None or int(nms_topk) <= 0 else \
        min(int(nms_topk), a)

    def per_sample(probs, deltas):
        # probs [cls, A]; deltas [A*4]
        boxes = _decode(anchors, deltas.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        mask = jnp.ones(probs.shape[0], bool).at[background_id].set(False)
        fg = jnp.where(mask[:, None], probs, -1.0)
        cls_id = jnp.argmax(fg, axis=0)                # [A]
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        if force_suppress:
            keep = _nms_mask(boxes, jnp.where(valid, score, -1.0), valid,
                             nms_threshold, topk)
        else:
            keep = valid
            n_cls = probs.shape[0]
            for c in range(n_cls):
                if c == background_id:
                    continue
                sel = valid & (cls_id == c)
                k = _nms_mask(boxes, jnp.where(sel, score, -1.0), sel,
                              nms_threshold, topk)
                keep = jnp.where(sel, k, keep)
        # class ids in output are 0-based foreground ids: classes above
        # background_id shift down by one (reference drops background)
        fg_id = jnp.where(cls_id > background_id, cls_id - 1, cls_id)
        out_id = jnp.where(keep, fg_id.astype(jnp.float32), -1.0)
        rows = jnp.concatenate([out_id[:, None], score[:, None], boxes],
                               axis=1)
        # compact: valid detections first, sorted by confidence descending
        # (multibox_detection.cc sorts kept rows by score before writing,
        # so consumers can read the first k rows)
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf))
        return rows[order]

    out = jax.vmap(per_sample)(cls_prob, loc_pred)
    return lax.stop_gradient(out)


# ---------------------------------------------------------------------------
# Proposal — contrib/proposal-inl.h (Faster R-CNN RPN proposals)
# ---------------------------------------------------------------------------

def _gen_base_anchors(base_size, scales, ratios):
    """Standard RPN base anchors around (0,0) (proposal-inl.h
    GenerateAnchor)."""
    px = (base_size - 1) * 0.5
    py = (base_size - 1) * 0.5
    anchors = []
    area = base_size * base_size
    for r in ratios:
        size_r = area / r
        ws = int(round(np.sqrt(size_r)))
        hs = int(round(ws * r))
        for s in scales:
            w = ws * s
            h = hs * s
            anchors.append([px - (w - 1) * 0.5, py - (h - 1) * 0.5,
                            px + (w - 1) * 0.5, py + (h - 1) * 0.5])
    return np.array(anchors, np.float32)


def _proposal_infer(attrs, in_shapes):
    cls_prob = in_shapes[0]
    if cls_prob is None:
        return list(in_shapes), [None], []
    post = int(attrs.get("rpn_post_nms_top_n", 300))
    n = cls_prob[0]
    outs = [(n * post, 5)]
    if attrs.get("output_score"):
        outs.append((n * post, 1))
    return list(in_shapes), outs, []


def _proposal_num_outputs(attrs):
    return 2 if attrs.get("output_score") else 1


@register("_contrib_Proposal", aliases=("Proposal",),
          input_names=("cls_prob", "bbox_pred", "im_info"),
          num_outputs=_proposal_num_outputs, infer_shape=_proposal_infer)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposal generation (proposal-inl.h ProposalOp): slide base
    anchors over the feature map, decode bbox_pred, clip to image, drop
    small boxes, take pre-NMS top-N by score, NMS, pad to post-NMS top-N."""
    n, two_a, h, w = cls_prob.shape
    scales = tuple(float(s) for s in (scales if isinstance(scales, (list, tuple)) else (scales,)))
    ratios = tuple(float(r) for r in (ratios if isinstance(ratios, (list, tuple)) else (ratios,)))
    base = _gen_base_anchors(int(feature_stride), scales, ratios)  # [A0, 4]
    a0 = base.shape[0]
    sy = jnp.arange(h, dtype=jnp.float32) * feature_stride
    sx = jnp.arange(w, dtype=jnp.float32) * feature_stride
    syg, sxg = jnp.meshgrid(sy, sx, indexing="ij")
    shift = jnp.stack([sxg, syg, sxg, syg], axis=-1)   # [h, w, 4]
    anchors = (shift[:, :, None, :] + base[None, None]).reshape(-1, 4)

    post = int(rpn_post_nms_top_n)
    pre = min(int(rpn_pre_nms_top_n), anchors.shape[0])

    def per_sample(probs, deltas, info):
        # probs [2*A0, h, w] (bg scores first A0 channels, fg last);
        # deltas [4*A0, h, w]
        fg = probs[a0:].transpose(1, 2, 0).reshape(-1)         # [h*w*A0]
        d = deltas.reshape(a0, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        # decode (unnormalized RPN parameterization: variances = 1)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        ww = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        hh = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - (ww - 1) * 0.5, cy - (hh - 1) * 0.5,
                           cx + (ww - 1) * 0.5, cy + (hh - 1) * 0.5],
                          axis=-1)
        im_h, im_w = info[0], info[1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                           jnp.clip(boxes[:, 1], 0, im_h - 1),
                           jnp.clip(boxes[:, 2], 0, im_w - 1),
                           jnp.clip(boxes[:, 3], 0, im_h - 1)], axis=-1)
        min_size = rpn_min_size * info[2]
        keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
                    ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        score = jnp.where(keep_size, fg, -1.0)
        top_score, top_idx = lax.top_k(score, pre)
        top_boxes = boxes[top_idx]
        valid = top_score > 0
        keep = _nms_mask(top_boxes, top_score, valid, threshold, pre)
        # order survivors by score, take post
        rank_score = jnp.where(keep, top_score, -1.0)
        sel_score, sel = lax.top_k(rank_score, post)
        out_boxes = jnp.where((sel_score > 0)[:, None], top_boxes[sel], 0.0)
        out_score = jnp.maximum(sel_score, 0.0)
        return out_boxes, out_score

    boxes, scores = jax.vmap(per_sample)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(n, dtype=jnp.float32), post)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(n * post, 4)], axis=1)
    rois = lax.stop_gradient(rois)
    if output_score:
        return rois, lax.stop_gradient(scores.reshape(n * post, 1))
    return rois


# ---------------------------------------------------------------------------
# FFT / IFFT — contrib/fft-inl.h (cuFFT): real input -> interleaved
# real/imag output of length 2d
# ---------------------------------------------------------------------------

def _fft_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return list(in_shapes), [None], []
    return [tuple(d)], [d[:-1] + (d[-1] * 2,)], []


@register("_contrib_fft", aliases=("fft",), infer_shape=_fft_infer)
def fft(data, compute_size=128):
    """FFT along the last dim; complex output interleaved [re, im, re, im...]
    (fft-inl.h output layout, 2*d)."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (data.shape[-1] * 2,)) \
        .astype(jnp.float32)


def _ifft_infer(attrs, in_shapes):
    d = in_shapes[0]
    if d is None:
        return list(in_shapes), [None], []
    return [tuple(d)], [d[:-1] + (d[-1] // 2,)], []


@register("_contrib_ifft", aliases=("ifft",), infer_shape=_ifft_infer)
def ifft(data, compute_size=128):
    """Inverse of _contrib_fft: interleaved complex -> real (the reference
    scales by n like cuFFT's unnormalized inverse divided in python)."""
    d = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (d, 2))
    z = c[..., 0] + 1j * c[..., 1]
    return jnp.fft.ifft(z, axis=-1).real.astype(jnp.float32) * d


# ---------------------------------------------------------------------------
# count_sketch — contrib/count_sketch-inl.h
# ---------------------------------------------------------------------------

def _cs_infer(attrs, in_shapes):
    d = in_shapes[0]
    out_dim = int(attrs["out_dim"])
    if d is None:
        return list(in_shapes), [None], []
    return list(in_shapes), [(d[0], out_dim)], []


@register("_contrib_count_sketch", aliases=("count_sketch",),
          input_names=("data", "h", "s"), infer_shape=_cs_infer)
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (count_sketch-inl.h): out[:, h[j]] +=
    s[j] * data[:, j].  h in [0, out_dim), s in {+1, -1}.  Linear, so the
    gradient falls out of autodiff through the scatter-add."""
    out_dim = int(out_dim)
    hj = h.reshape(-1).astype(jnp.int32)
    sj = s.reshape(-1).astype(data.dtype)
    vals = data * sj[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., hj].add(vals)


# ---------------------------------------------------------------------------
# Attention — new capability beyond the reference (2017 had none).  The
# symbol-level entry to the flash-style attention in parallel/
# ring_attention.py: under a GSPMD-sharded trainer the sequence axis
# partitions automatically; for the explicit ring schedule over 'sp' use
# parallel.ring_attention directly.
# ---------------------------------------------------------------------------

def _attention_infer(attrs, in_shapes):
    q = in_shapes[0]
    if q is None:
        return list(in_shapes), [None], []
    return [tuple(s) if s is not None else None for s in in_shapes], \
        [tuple(q)], []


@register("_contrib_Attention", aliases=("Attention", "attention"),
          input_names=("query", "key", "value"),
          infer_shape=_attention_infer)
def contrib_attention(query, key, value, num_heads=1, causal=False,
                      scale=-1.0):
    """Multi-head scaled-dot-product attention (numerically-stable
    softmax; materializes the (Tq, Tk) score matrix — for long-context
    O(T/sp) memory use parallel.ring_attention over an 'sp' mesh axis).
    query/key/value: (batch, seq, d_model); heads split from d_model.
    Output: (batch, seq_q, d_model)."""
    from ..parallel.ring_attention import full_attention
    num_heads = int(num_heads)
    B, T, D = query.shape
    Tk = key.shape[1]
    if D % num_heads != 0:
        raise MXNetError("d_model %d not divisible by num_heads %d"
                         % (D, num_heads))
    if causal and T > Tk:
        raise MXNetError(
            "causal attention needs seq_q (%d) <= seq_k (%d): earlier "
            "query positions would have no visible keys" % (T, Tk))
    hd = D // num_heads
    q = query.reshape(B, T, num_heads, hd)
    k = key.reshape(B, Tk, num_heads, hd)
    v = value.reshape(B, Tk, num_heads, hd)
    s = None if float(scale) <= 0 else float(scale)
    out = full_attention(q, k, v, causal=bool(causal), scale=s)
    return out.reshape(B, T, D)
