"""Library locator (reference python/mxnet/libinfo.py find_lib_path):
returns the native engine/recordio shared library this build loads."""
import os

__version__ = "0.1.0"


def find_lib_path():
    """Paths of the native libraries backing this install (the analog of
    locating libmxnet.so)."""
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [os.path.join(here, "native", "libmxtpu.so")]
    found = [p for p in candidates if os.path.exists(p)]
    if not found:
        raise RuntimeError(
            "native library not found (expected %s); the Python engine "
            "fallback is used automatically" % candidates)
    return found
