"""Detection data pipeline: label-aware augmenters + ImageDetRecordIter.

Capability port of the reference's detection IO stack
(src/io/iter_image_det_recordio.cc:563 + image_det_aug_default.cc): records
are packed by tools/im2rec.py with a flat detection label
``[header_width, object_width, (id, xmin, ymin, xmax, ymax, ...) * N]``
(coords normalized to [0, 1]); the iterator emits

- data:  (batch, C, H, W) float32
- label: (batch, label_pad_width + 4) where each row is filled with
  ``label_pad_value`` and carries ``[channels, rows, cols, label_len,
  *flat_label]`` (iter_image_det_recordio.cc:436-444)

Augmenters transform image AND boxes together (random IOU-constrained
crop, random expand/pad, horizontal mirror, forced resize — the core of
image_det_aug_default.cc's sampler set).
"""
from __future__ import annotations

import logging
import random as pyrandom

import numpy as np

from .base import MXNetError
from . import io as mxio
from . import recordio
from .image import color_normalize, imdecode, imresize
from .io import DataBatch, DataDesc
from .ndarray import array as nd_array

__all__ = ["DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "DetForceResizeAug", "CreateDetAugmenter", "ImageDetRecordIter"]


class _DetLabel(object):
    """Parsed detection label: header + (N, object_width) objects with
    columns [id, xmin, ymin, xmax, ymax, extra...]."""

    def __init__(self, flat):
        flat = np.asarray(flat, dtype=np.float32)
        if flat.size < 2:
            raise MXNetError("detection label too short: %r" % (flat,))
        self.header_width = int(flat[0])
        self.object_width = int(flat[1])
        if self.header_width < 2 or self.object_width < 5:
            raise MXNetError(
                "bad detection label header (header_width=%d, "
                "object_width=%d); expected [header_width, object_width, "
                "id x1 y1 x2 y2 ...]" % (self.header_width,
                                         self.object_width))
        self.header = flat[:self.header_width]
        body = flat[self.header_width:]
        n = body.size // self.object_width
        self.objects = body[:n * self.object_width].reshape(
            n, self.object_width).copy()

    def flat(self):
        return np.concatenate([self.header, self.objects.reshape(-1)])


def _overlap_1d(a0, a1, b0, b1):
    return max(0.0, min(a1, b1) - max(a0, b0))


def _iou(box, crop):
    inter = _overlap_1d(box[0], box[2], crop[0], crop[2]) * \
        _overlap_1d(box[1], box[3], crop[1], crop[3])
    if inter <= 0:
        return 0.0
    area_a = (box[2] - box[0]) * (box[3] - box[1])
    area_b = (crop[2] - crop[0]) * (crop[3] - crop[1])
    return inter / (area_a + area_b - inter)


def DetHorizontalFlipAug(p):
    """Mirror image and boxes together (image_det_aug_default.cc
    rand_mirror_prob)."""
    def aug(src, label):
        if pyrandom.random() < p:
            src = src[:, ::-1]
            boxes = label.objects
            xmin = boxes[:, 1].copy()
            boxes[:, 1] = 1.0 - boxes[:, 3]
            boxes[:, 3] = 1.0 - xmin
        return src, label
    return aug


def DetRandomCropAug(min_scale=0.3, max_scale=1.0, min_aspect=0.5,
                     max_aspect=2.0, min_overlap=0.1, max_trials=25,
                     prob=0.5):
    """IOU-constrained random crop (the reference's crop sampler,
    image_det_aug_default.cc min_crop_scales/min_crop_overlaps): sample a
    crop window whose IOU with at least one ground-truth box exceeds
    ``min_overlap``; objects whose center falls outside are dropped, the
    rest are clipped and re-normalized to the crop."""
    def aug(src, label):
        if pyrandom.random() >= prob or len(label.objects) == 0:
            return src, label
        h, w = src.shape[:2]
        for _ in range(max_trials):
            scale = pyrandom.uniform(min_scale, max_scale)
            ratio = pyrandom.uniform(min_aspect, max_aspect)
            cw = min(1.0, scale * np.sqrt(ratio))
            ch = min(1.0, scale / np.sqrt(ratio))
            cx = pyrandom.uniform(0, 1 - cw)
            cy = pyrandom.uniform(0, 1 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            ious = [_iou(b[1:5], crop) for b in label.objects]
            if max(ious) < min_overlap:
                continue
            # keep objects whose center is inside the crop
            kept = []
            for b in label.objects:
                ctr_x = (b[1] + b[3]) / 2
                ctr_y = (b[2] + b[4]) / 2
                if not (crop[0] <= ctr_x <= crop[2]
                        and crop[1] <= ctr_y <= crop[3]):
                    continue
                nb = b.copy()
                nb[1] = (min(max(b[1], crop[0]), crop[2]) - cx) / cw
                nb[2] = (min(max(b[2], crop[1]), crop[3]) - cy) / ch
                nb[3] = (min(max(b[3], crop[0]), crop[2]) - cx) / cw
                nb[4] = (min(max(b[4], crop[1]), crop[3]) - cy) / ch
                kept.append(nb)
            if not kept:
                continue
            x0, y0 = int(cx * w), int(cy * h)
            x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
            src = src[y0:max(y1, y0 + 1), x0:max(x1, x0 + 1)]
            label.objects = np.asarray(kept, dtype=np.float32)
            return src, label
        return src, label
    return aug


def DetRandomPadAug(max_scale=2.0, fill_value=127, prob=0.5):
    """Random expand: place the image on a larger canvas and shrink the
    boxes accordingly (image_det_aug_default.cc rand_pad_prob /
    max_pad_scale) — the standard SSD small-object augmentation."""
    def aug(src, label):
        if pyrandom.random() >= prob or max_scale <= 1.0:
            return src, label
        h, w = src.shape[:2]
        scale = pyrandom.uniform(1.0, max_scale)
        nh, nw = int(h * scale), int(w * scale)
        y0 = pyrandom.randint(0, nh - h)
        x0 = pyrandom.randint(0, nw - w)
        canvas = np.full((nh, nw, src.shape[2]), fill_value, dtype=src.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = src
        boxes = label.objects
        boxes[:, 1] = (boxes[:, 1] * w + x0) / nw
        boxes[:, 3] = (boxes[:, 3] * w + x0) / nw
        boxes[:, 2] = (boxes[:, 2] * h + y0) / nh
        boxes[:, 4] = (boxes[:, 4] * h + y0) / nh
        return canvas, label
    return aug


def DetForceResizeAug(size, interp=1):
    """Force resize to (w, h); normalized boxes are unchanged
    (resize_mode='force', image_det_aug_default.cc)."""
    def aug(src, label):
        return imresize(src, size[0], size[1], interp), label
    return aug


def CreateDetAugmenter(data_shape, resize=0, rand_crop_prob=0,
                       min_crop_scales=0.3, max_crop_scales=1.0,
                       min_crop_overlaps=0.1, max_crop_trials=25,
                       rand_pad_prob=0, max_pad_scale=2.0,
                       rand_mirror_prob=0, fill_value=127, inter_method=1,
                       mean=None, std=None):
    """Standard detection augmenter list (the reference's
    ListDefaultDetAugParams surface, simplified to one crop sampler)."""
    auglist = []
    if rand_crop_prob > 0:
        auglist.append(DetRandomCropAug(
            min_scale=min_crop_scales, max_scale=max_crop_scales,
            min_overlap=min_crop_overlaps, max_trials=max_crop_trials,
            prob=rand_crop_prob))
    if rand_pad_prob > 0:
        auglist.append(DetRandomPadAug(max_scale=max_pad_scale,
                                       fill_value=fill_value,
                                       prob=rand_pad_prob))
    if rand_mirror_prob > 0:
        auglist.append(DetHorizontalFlipAug(rand_mirror_prob))
    # detection always force-resizes to the network input
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1]),
                                     inter_method))
    if mean is not None or std is not None:
        def norm_aug(src, label, _m=mean, _s=std):
            return color_normalize(src, _m, _s), label
        auglist.append(norm_aug)
    return auglist


class ImageDetRecordIter(mxio.DataIter):
    """RecordIO detection iterator (reference
    iter_image_det_recordio.cc:ImageDetRecordIter).

    Reads im2rec-packed records whose header label is the flat detection
    format; applies the label-aware augmenter chain; emits padded labels
    ``(batch, label_pad_width + 4)`` with the [channels, rows, cols, len]
    prologue, exactly like the reference parser.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_pad_width=0, label_pad_value=-1.0,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=0.0, std_g=0.0, std_b=0.0,
                 data_name="data", label_name="label", verbose=False,
                 **aug_kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_pad_value = float(label_pad_value)
        self.data_name = data_name
        self.label_name = label_name
        if path_imgidx:
            self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                   "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
            self._keys = None
            if shuffle:
                logging.warning(
                    "ImageDetRecordIter: shuffle requires path_imgidx (the "
                    "stream reader is sequential); iterating in file order")
        self.shuffle = shuffle
        self._stream_part = None
        if num_parts > 1:
            if self._keys is not None:
                chunk = len(self._keys) // num_parts
                self._keys = self._keys[part_index * chunk:
                                        (part_index + 1) * chunk]
            else:
                # shard the sequential stream by record position
                self._stream_part = (part_index, num_parts)
        mean = [mean_r, mean_g, mean_b] if any([mean_r, mean_g, mean_b]) \
            else None
        std = [std_r, std_g, std_b] if any([std_r, std_g, std_b]) else None
        if aug_list is None:
            aug_list = CreateDetAugmenter(self.data_shape, mean=mean,
                                          std=std, **aug_kwargs)
        self.auglist = aug_list

        # estimate the label padding width over the whole file, like the
        # reference's pre-scan (iter_image_det_recordio.cc:269-316)
        max_width = self._scan_max_label_width()
        if max_width > label_pad_width:
            if label_pad_width > 0:
                raise MXNetError(
                    "ImageDetRecordIter: label_pad_width %d smaller than "
                    "estimated width %d" % (label_pad_width, max_width))
            label_pad_width = max_width
        self.label_pad_width = label_pad_width
        if verbose:
            logging.info("ImageDetRecordIter: %s, label padding width: %d",
                         path_imgrec, label_pad_width)
        self._cursor = 0
        self.reset()

    def _scan_max_label_width(self):
        width = 0
        self._rec.reset()
        while True:
            s = self._rec.read()
            if s is None:
                break
            header, _ = recordio.unpack(s)
            label = np.asarray(header.label)
            if label.ndim == 0 or label.size < 2:
                raise MXNetError("record without a detection label")
            width = max(width, label.size)
        self._rec.reset()
        return int(width)

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.label_pad_width + 4))]

    def reset(self):
        if self._keys is not None and self.shuffle:
            pyrandom.shuffle(self._keys)
        self._cursor = 0
        self._rec.reset()

    def _next_record(self):
        if self._keys is not None:
            if self._cursor >= len(self._keys):
                return None
            s = self._rec.read_idx(self._keys[self._cursor])
            self._cursor += 1
            return s
        while True:
            s = self._rec.read()
            if s is None or self._stream_part is None:
                return s
            part, nparts = self._stream_part
            pos = self._cursor
            self._cursor += 1
            if pos % nparts == part:
                return s

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        labels = np.full((self.batch_size, self.label_pad_width + 4),
                         self.label_pad_value, dtype=np.float32)
        n = 0
        while n < self.batch_size:
            s = self._next_record()
            if s is None:
                break
            header, img = recordio.unpack(s)
            try:
                arr = imdecode(img)
            except (RuntimeError, MXNetError) as e:
                logging.debug("Invalid image, skipping: %s", str(e))
                continue
            label = _DetLabel(np.asarray(header.label))
            for aug in self.auglist:
                arr, label = aug(arr, label)
            flat = label.flat()
            if flat.size > self.label_pad_width:
                raise MXNetError(
                    "augmented label width %d exceeds label_pad_width %d "
                    "(an augmenter added boxes?); construct the iterator "
                    "with an explicit larger label_pad_width"
                    % (flat.size, self.label_pad_width))
            data[n] = np.asarray(arr, dtype=np.float32).transpose(2, 0, 1)
            labels[n, 0] = arr.shape[2] if arr.ndim == 3 else 1
            labels[n, 1] = arr.shape[0]
            labels[n, 2] = arr.shape[1]
            labels[n, 3] = flat.size
            labels[n, 4:4 + flat.size] = flat
            n += 1
        if n == 0:
            raise StopIteration
        pad = self.batch_size - n
        return DataBatch(data=[nd_array(data)], label=[nd_array(labels)],
                         pad=pad, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    __next__ = next
