"""Host-side dependency engine.

TPU-native re-design of the reference's execution engine (reference
include/mxnet/engine.h:75-229, src/engine/threaded_engine.h:44-394,
src/engine/naive_engine.cc).  On TPU, *device-side* scheduling belongs to
XLA's async dispatch — jax.Array operations are already ordered by the
runtime — so this engine is the concurrency authority for **host work**:
data-pipeline stages, RecordIO/checkpoint IO, kvstore host ops and Python
callbacks.  The observable contract is the reference's:

* an op declares ``const_vars`` (reads) and ``mutable_vars`` (writes);
* reads of a var may run concurrently; a write serializes against all
  other access, in push order;
* ``wait_for_var`` blocks until everything already pushed touching the var
  has completed; ``wait_for_all`` drains the engine;
* variable deletion is dependency-ordered.

Two backends: the native C++ engine (mxnet_tpu/native/engine.cc, threaded
pool) loaded via ctypes, and a pure-Python fallback with identical
semantics.  ``MXNET_ENGINE_TYPE`` selects ``ThreadedEngine`` (default) or
``NaiveEngine`` (synchronous, for debugging — reference
src/engine/engine.cc:14-27).
"""
from __future__ import annotations

import ctypes
import itertools
import json
import threading
import traceback
from collections import deque

from . import native
from .base import MXNetError, get_env, register_env

ENV_ENGINE_TYPE = register_env(
    "MXNET_ENGINE_TYPE", default="ThreadedEngine",
    doc="Host dependency engine; NaiveEngine serializes every op on the "
        "caller thread for debugging")

__all__ = ["Engine", "get", "set_engine_type", "EngineVar"]


class EngineVar(object):
    """Opaque dependency variable handle."""

    __slots__ = ("id", "_engine")

    def __init__(self, var_id, engine):
        self.id = var_id
        self._engine = engine


class _NativeEngine(object):
    """ctypes wrapper over the C++ engine (native/engine.cc)."""

    def __init__(self, naive=False, num_workers=0):
        self._lib = native.get_lib()
        assert self._lib is not None
        self._handle = self._lib.MXTPUEngineCreate(0 if naive else 1,
                                                   num_workers)
        self._cb_lock = threading.Lock()
        self._callbacks = {}
        self._counter = itertools.count(1)
        self._errors = []
        # The dispatcher must outlive every pending op; bind it to self.
        self._dispatcher = native.ENGINE_CB(self._dispatch)
        self._closed = False

    def _dispatch(self, payload):
        token = int(payload)
        with self._cb_lock:
            fn = self._callbacks.pop(token, None)
        if fn is None:
            return
        try:
            fn()
        except BaseException:  # never propagate into C++
            with self._cb_lock:
                self._errors.append(traceback.format_exc())

    def _check_errors(self):
        with self._cb_lock:
            errs, self._errors = self._errors, []
        if errs:
            raise MXNetError(
                "engine op(s) raised:\n%s" % "\n---\n".join(errs))

    def new_variable(self):
        return EngineVar(self._lib.MXTPUEngineNewVar(self._handle), self)

    def delete_variable(self, var):
        self._lib.MXTPUEngineDeleteVar(self._handle, var.id)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0, name=""):
        token = next(self._counter)
        with self._cb_lock:
            self._callbacks[token] = fn
        n_c, n_m = len(const_vars), len(mutable_vars)
        c_arr = (ctypes.c_uint64 * max(n_c, 1))(*[v.id for v in const_vars])
        m_arr = (ctypes.c_uint64 * max(n_m, 1))(*[v.id for v in mutable_vars])
        ret = self._lib.MXTPUEnginePushAsync(
            self._handle, self._dispatcher, ctypes.c_void_p(token),
            c_arr, n_c, m_arr, n_m, priority, name.encode())
        if ret != 0:
            with self._cb_lock:
                self._callbacks.pop(token, None)
            err = self._lib.MXTPUEngineLastError(self._handle)
            raise MXNetError("engine push failed: %s"
                            % (err.decode() if err else "unknown"))

    def wait_for_var(self, var):
        self._lib.MXTPUEngineWaitForVar(self._handle, var.id)
        self._check_errors()

    def wait_for_all(self):
        self._lib.MXTPUEngineWaitForAll(self._handle)
        self._check_errors()

    def num_pending(self):
        return self._lib.MXTPUEngineNumPending(self._handle)

    def set_profiler_state(self, running):
        self._lib.MXTPUProfilerSetState(self._handle, 1 if running else 0)

    def dump_profile(self):
        ptr = self._lib.MXTPUProfilerDump(self._handle)
        try:
            return ctypes.string_at(ptr).decode()
        finally:
            self._lib.MXTPUFree(ptr)

    def shutdown(self):
        if not self._closed:
            self._closed = True
            self._lib.MXTPUEngineWaitForAll(self._handle)
            self._lib.MXTPUEngineShutdown(self._handle)

    @property
    def is_native(self):
        return True


class _PyVar(object):
    __slots__ = ("queue", "running_reads", "write_granted", "version")

    def __init__(self):
        self.queue = deque()
        self.running_reads = 0
        self.write_granted = False
        self.version = 0


class _PyOpr(object):
    __slots__ = ("fn", "const_vars", "mutable_vars", "wait", "priority",
                 "name", "seq")

    def __init__(self):
        self.wait = 0


class _PythonEngine(object):
    """Pure-Python engine with the same semantics (fallback backend)."""

    def __init__(self, naive=False, num_workers=0):
        self._naive = naive
        self._lock = threading.Lock()
        self._pending = 0
        self._all_done = threading.Condition(self._lock)
        self._errors = []
        self._profiling = False
        self._events = []
        self._seq = itertools.count()
        if not naive:
            if num_workers <= 0:
                import os as _os
                # Host work is IO-bound; keep a floor above core count.
                num_workers = max(4, min(16, _os.cpu_count() or 4))
            self._ready = deque()
            self._ready_cv = threading.Condition()
            self._stop = False
            self._workers = [
                threading.Thread(target=self._worker_loop, daemon=True)
                for _ in range(num_workers)]
            for t in self._workers:
                t.start()

    def new_variable(self):
        return EngineVar(_PyVar(), self)

    def delete_variable(self, var):
        # Dependency-ordered no-op: Python GC owns reclamation.
        self.push(lambda: None, mutable_vars=(var,), name="DeleteVariable")

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0, name=""):
        cset = {id(v) for v in const_vars}
        for v in mutable_vars:
            if id(v) in cset:
                raise MXNetError("var appears in both const and mutable list")
        if len({id(v) for v in mutable_vars}) != len(mutable_vars) or \
                len(cset) != len(const_vars):
            raise MXNetError("duplicate var in dependency list")
        op = _PyOpr()
        op.fn = fn
        op.const_vars = [v.id for v in const_vars]
        op.mutable_vars = [v.id for v in mutable_vars]
        op.priority = priority
        op.name = name
        op.seq = next(self._seq)
        with self._lock:
            self._pending += 1
        op.wait = 1 + len(op.const_vars) + len(op.mutable_vars)
        for v in op.const_vars:
            self._append_dep(v, op, write=False)
        for v in op.mutable_vars:
            self._append_dep(v, op, write=True)
        self._on_granted(op)

    def _append_dep(self, v, op, write):
        grant = False
        with self._lock:
            if write:
                if not v.queue and v.running_reads == 0 and \
                        not v.write_granted:
                    v.write_granted = True
                    grant = True
                else:
                    v.queue.append((op, True))
            else:
                if not v.queue and not v.write_granted:
                    v.running_reads += 1
                    grant = True
                else:
                    v.queue.append((op, False))
        if grant:
            self._on_granted(op)

    def _complete_access(self, v, write):
        granted = []
        with self._lock:
            if write:
                v.write_granted = False
                v.version += 1
            else:
                v.running_reads -= 1
            while v.queue:
                op, w = v.queue[0]
                if w:
                    if v.running_reads == 0 and not v.write_granted:
                        v.write_granted = True
                        granted.append(op)
                        v.queue.popleft()
                    break
                if v.write_granted:
                    break
                v.running_reads += 1
                granted.append(op)
                v.queue.popleft()
        for op in granted:
            self._on_granted(op)

    def _on_granted(self, op):
        with self._lock:
            op.wait -= 1
            fire = op.wait == 0
        if fire:
            if self._naive:
                self._execute(op)
            else:
                with self._ready_cv:
                    self._ready.append(op)
                    self._ready_cv.notify()

    def _execute(self, op):
        import time
        start = time.time() if self._profiling else 0
        try:
            op.fn()
        except BaseException:
            with self._lock:
                self._errors.append(traceback.format_exc())
        if self._profiling:
            end = time.time()
            with self._lock:
                self._events.append((op.name or "op", int(start * 1e6),
                                     int(end * 1e6),
                                     threading.get_ident()))
        for v in op.const_vars:
            self._complete_access(v, write=False)
        for v in op.mutable_vars:
            self._complete_access(v, write=True)
        with self._lock:
            self._pending -= 1
            if self._pending == 0:
                self._all_done.notify_all()

    def _worker_loop(self):
        while True:
            with self._ready_cv:
                while not self._ready and not self._stop:
                    self._ready_cv.wait()
                if self._stop and not self._ready:
                    return
                op = self._ready.popleft()
            self._execute(op)

    def wait_for_var(self, var):
        done = threading.Event()
        self.push(done.set, const_vars=(var,), name="WaitForVar")
        done.wait()
        self._check_errors()

    def wait_for_all(self):
        with self._lock:
            while self._pending:
                self._all_done.wait()
        self._check_errors()

    def _check_errors(self):
        with self._lock:
            errs, self._errors = self._errors, []
        if errs:
            raise MXNetError(
                "engine op(s) raised:\n%s" % "\n---\n".join(errs))

    def num_pending(self):
        with self._lock:
            return self._pending

    def set_profiler_state(self, running):
        self._profiling = bool(running)

    def dump_profile(self):
        with self._lock:
            events = list(self._events)
        trace = []
        for name, start, end, tid in events:
            trace.append({"name": name, "cat": "operator", "ph": "B",
                          "ts": start, "pid": 0, "tid": tid})
            trace.append({"name": name, "cat": "operator", "ph": "E",
                          "ts": end, "pid": 0, "tid": tid})
        return json.dumps({"traceEvents": trace, "displayTimeUnit": "ms"},
                          indent=2)

    def shutdown(self):
        self.wait_for_all()
        if not self._naive:
            with self._ready_cv:
                self._stop = True
                self._ready_cv.notify_all()

    @property
    def is_native(self):
        return False


class Engine(object):
    """Facade choosing the native or Python backend."""

    def __new__(cls, engine_type=None, num_workers=0, force_python=False):
        if engine_type is None:
            engine_type = get_env(ENV_ENGINE_TYPE, "ThreadedEngine")
        naive = "naive" in engine_type.lower()
        if not force_python and native.get_lib() is not None:
            inst = _NativeEngine(naive=naive, num_workers=num_workers)
        else:
            inst = _PythonEngine(naive=naive, num_workers=num_workers)
        _track(inst)
        return inst


_engine = None
_engine_lock = threading.RLock()
_all_engines = None
_atexit_registered = False


def _track(inst):
    """Every engine (incl. private ones owned by data iterators) must be
    drained and stopped before interpreter teardown — native workers left
    running abort the process ('terminate called ...')."""
    global _all_engines, _atexit_registered
    import weakref
    with _engine_lock:
        if _all_engines is None:
            _all_engines = weakref.WeakSet()
        _all_engines.add(inst)
        if not _atexit_registered:
            import atexit
            atexit.register(_shutdown_global)
            _atexit_registered = True


def _shutdown_global():
    global _engine
    with _engine_lock:
        for eng in list(_all_engines or ()):
            try:
                eng.shutdown()
            except Exception:
                pass
        _engine = None


def get():
    """The process-global engine (reference Engine::Get())."""
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = Engine()
    return _engine


def set_engine_type(engine_type):
    """Replace the global engine (drains and stops the old one first)."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.shutdown()
        else:
            import atexit
            atexit.register(_shutdown_global)
        _engine = Engine(engine_type)
    return _engine
