"""RNN cell library (reference python/mxnet/rnn/rnn_cell.py:60-608).

Cells build symbolic graphs step-by-step via ``unroll``; ``FusedRNNCell``
emits the fused RNN op (one lax.scan — the cuDNN-RNN analog) and
``unfuse()`` converts it to a SequentialRNNCell of explicit cells.  Weight
layout pack/unpack between the fused vector and per-cell i2h/h2h matrices
round-trips (gate order LSTM [i,f,c,o], GRU [r,z,n] — ops/nn.py RNN).
"""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from .. import symbol
from ..base import MXNetError
from ..ops.nn import _RNN_GATES, rnn_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell"]


class RNNParams(object):
    """Container for cell parameters (reference rnn_cell.py:RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract cell (reference rnn_cell.py:BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, init_sym=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            state = symbol.Variable("%sbegin_state_%d"
                                    % (self._prefix, self._init_counter))
            states.append(state)
        return states

    def _zeros_begin_state(self, ref_batch_first):
        """Default zero initial states, shaped from a reference input symbol
        whose axis 0 is the batch (the reference's
        ``begin_state(func=symbol.zeros)`` with shape (0, H): the unknown
        batch dim resolves forward from the data instead of needing the
        reference's bidirectional shape solver)."""
        states = []
        for info in self.state_info:
            shape = info["shape"]
            known = [int(d) for d in shape if d != 0]
            total = 1
            for d in known:
                total *= d
            base = symbol.Reshape(ref_batch_first * 0, shape=(0, -1))
            z = symbol.sum(base, axis=1, keepdims=True)       # (B, 1)
            z = symbol.tile(z, reps=(1, total))               # (B, prod)
            if len(shape) == 2:
                pass                                          # (B, H)
            elif len(shape) == 3 and shape[1] == 0:
                # fused layout (L*D, B, H): batch in the middle
                z = symbol.Reshape(z, shape=(0, shape[0], shape[2]))
                z = symbol.SwapAxis(z, dim1=0, dim2=1)
            else:
                raise MXNetError(
                    "cannot derive a zero begin state for state shape %s"
                    % (shape,))
            states.append(z)
        return states

    def unpack_weights(self, args):
        """fused vector -> per-gate i2h/h2h dict (rnn_cell.py:unpack_weights)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.concatenate(bias)
        return args

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Unroll the cell over ``length`` steps (rnn_cell.py:unroll)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            inputs = symbol.SliceChannel(inputs, axis=axis,
                                         num_outputs=length,
                                         squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            # reference default: zeros (begin_state(func=symbol.zeros));
            # shaped from the data so shapes resolve forward
            begin_state = self._zeros_begin_state(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=axis) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (rnn_cell.py:RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (rnn_cell.py:LSTMCell); gate order [i, f, c, o]."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        import json
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        # forget gate opens at sigmoid(forget_bias) via the LSTMBias
        # initializer attr (reference rnn_cell.py:388 init.LSTMBias)
        self._iB = self.params.get(
            "i2h_bias",
            __init__=json.dumps(["lstmbias", {"forget_bias": forget_bias}]))
        self._hB = self.params.get("h2h_bias")
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        from ..kernels import fused_enabled
        if fused_enabled("lstm_cell"):
            # one-kernel gate math (mxnet_tpu/kernels/lstm_cell.py);
            # MXTPU_FUSED_KERNELS=0 at symbol-build time restores the
            # exact slice/activation graph below (parity-tested)
            fused = symbol._FusedLSTMCell(gates, states[1],
                                          name="%sfused" % name)
            next_h, next_c = fused[0], fused[1]
            return next_h, [next_h, next_c]
        slice_gates = symbol.SliceChannel(gates, num_outputs=4, axis=1,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (rnn_cell.py:GRUCell); gate order [r, z, n]."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h = symbol.SliceChannel(i2h, num_outputs=3, axis=1,
                                  name="%si2h_slice" % name)
        h2h = symbol.SliceChannel(h2h, num_outputs=3, axis=1,
                                  name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h[0] + h2h[0], act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h[1] + h2h[1], act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h[2] + reset_gate * h2h[2],
                                       act_type="tanh", name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN (rnn_cell.py:FusedRNNCell) — emits the RNN op
    (ops/nn.py rnn: lax.scan with hoisted input projections)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")
        self._directions = ["l", "r"] if bidirectional else ["l"]

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _slice_weights(self, arr, li, lh):
        """Slice the fused vector into per-layer/direction/gate views
        (rnn_cell.py:_slice_weights) — mirrors ops/nn.py _rnn_split_params."""
        args = {}
        gate_names = self._gate_names
        directions = self._directions
        b = len(directions)
        p = 0
        for layer in range(self._num_layers):
            for direction in directions:
                for gate in gate_names:
                    name = "%s%s%d_i2h%s_weight" % (self._prefix, direction,
                                                    layer, gate)
                    size = (li if layer == 0 else lh * b) * lh
                    args[name] = arr[p:p + size].reshape(
                        (lh, li if layer == 0 else lh * b))
                    p += size
                for gate in gate_names:
                    name = "%s%s%d_h2h%s_weight" % (self._prefix, direction,
                                                    layer, gate)
                    size = lh ** 2
                    args[name] = arr[p:p + size].reshape((lh, lh))
                    p += size
        for layer in range(self._num_layers):
            for direction in directions:
                for gate in gate_names:
                    name = "%s%s%d_i2h%s_bias" % (self._prefix, direction,
                                                  layer, gate)
                    args[name] = arr[p:p + lh]
                    p += lh
                for gate in gate_names:
                    name = "%s%s%d_h2h%s_bias" % (self._prefix, direction,
                                                  layer, gate)
                    args[name] = arr[p:p + lh]
                    p += lh
        assert p == arr.size, "Invalid parameters size for FusedRNNCell"
        return args

    def unpack_weights(self, args):
        args = args.copy()
        arr = args.pop(self._parameter.name)
        b = len(self._directions)
        h = self._num_hidden
        g = self._num_gates
        L = self._num_layers
        # solve input size from total param count
        # total = b*g*h*(i + h + 2) + (L-1)*b*g*h*(b*h + h + 2)
        rest = arr.size - (L - 1) * b * g * h * (b * h + h + 2)
        num_input = rest // (b * g * h) - h - 2
        nargs = self._slice_weights(arr, num_input, self._num_hidden)
        args.update({name: nd.array(a) if not isinstance(a, nd.NDArray) else a.copy()
                     for name, a in nargs.items()})
        return args

    def pack_weights(self, args):
        args = args.copy()
        w0 = args["%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])]
        num_input = w0.shape[1]
        total = rnn_param_size(self._num_layers, num_input, self._num_hidden,
                               self._bidirectional, self._mode)
        flat = np.zeros(total, dtype="float32")
        p = 0
        # re-walk the same order, writing values in
        gate_names = self._gate_names
        b = len(self._directions)
        lh = self._num_hidden
        for layer in range(self._num_layers):
            for direction in self._directions:
                for kind in ("i2h", "h2h"):
                    for gate in gate_names:
                        name = "%s%s%d_%s%s_weight" % (
                            self._prefix, direction, layer, kind, gate)
                        w = args.pop(name).asnumpy().reshape(-1)
                        flat[p:p + w.size] = w
                        p += w.size
        for layer in range(self._num_layers):
            for direction in self._directions:
                for kind in ("i2h", "h2h"):
                    for gate in gate_names:
                        name = "%s%s%d_%s%s_bias" % (
                            self._prefix, direction, layer, kind, gate)
                        bias = args.pop(name).asnumpy().reshape(-1)
                        flat[p:p + bias.size] = bias
                        p += bias.size
        args[self._parameter.name] = nd.array(flat)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError("FusedRNNCell cannot be stepped. Please "
                                  "use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        if isinstance(inputs, (list, tuple)):
            inputs = [symbol.expand_dims(i, axis=1) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=1)
            axis = 1
        if axis == 1:
            # NTC -> TNC for the fused op
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            # inputs is TNC here; the zero-state builder wants batch-first
            begin_state = self._zeros_begin_state(
                symbol.SwapAxis(inputs, dim1=0, dim2=1))
        states = begin_state
        if self._mode == "lstm":
            states = {"state": states[0], "state_cell": states[1]}
        else:
            states = {"state": states[0]}
        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **states)
        attr_states = []
        if not self._get_next_state:
            outputs = rnn
        elif self._mode == "lstm":
            outputs, attr_states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, attr_states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = symbol.SliceChannel(
                outputs, axis=axis, num_outputs=length, squeeze_axis=1)
            outputs = [outputs[i] for i in range(length)]
        return outputs, attr_states

    def unfuse(self):
        """Fused -> stack of explicit cells (rnn_cell.py:unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, activation="relu",
                                          prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, activation="tanh",
                                          prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix,
                                                                i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (rnn_cell.py:SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs=None, begin_state=None, **kwargs):
        self.reset()
        states = begin_state
        outputs = inputs
        p = 0
        merge = kwargs.pop("merge_outputs", None)
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            outputs, st = cell.unroll(
                length, inputs=outputs,
                begin_state=None if states is None else states[p:p + n],
                merge_outputs=None if i < len(self._cells) - 1 else merge,
                **kwargs)
            next_states.extend(st)
            p += n
        return outputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout between layers (rnn_cell.py:DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (rnn_cell.py:ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, init_sym=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (rnn_cell.py:ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = (lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p))

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0.0 \
            else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Residual connection around a cell (rnn_cell.py:ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states


class BidirectionalCell(BaseRNNCell):
    """Bidirectional wrapper (rnn_cell.py:BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please "
                                  "use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=None if begin_state is None else begin_state[:n_l],
            layout=layout, merge_outputs=False, input_prefix=input_prefix)
        rev_inputs = list(reversed(inputs)) if isinstance(inputs, list) \
            else symbol.SequenceReverse(symbol.SwapAxis(inputs, dim1=0,
                                                        dim2=1))
        if not isinstance(rev_inputs, list):
            rev_inputs = symbol.SwapAxis(rev_inputs, dim1=0, dim2=1)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=rev_inputs,
            begin_state=None if begin_state is None else begin_state[n_l:],
            layout=layout, merge_outputs=False, input_prefix=input_prefix)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, l_states + r_states
