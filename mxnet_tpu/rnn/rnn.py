"""RNN checkpoint helpers (reference python/mxnet/rnn/rnn.py)."""
from __future__ import annotations

from .. import model
from .rnn_cell import BaseRNNCell

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _as_list(cells):
    if isinstance(cells, BaseRNNCell):
        return [cells]
    return cells


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save checkpoint with cell weights unpacked to per-gate form
    (reference rnn/rnn.py:save_rnn_checkpoint)."""
    args = arg_params
    for cell in _as_list(cells):
        args = cell.unpack_weights(args)
    model.save_checkpoint(prefix, epoch, symbol, args, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load checkpoint, packing per-gate weights into fused form."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    for cell in _as_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
