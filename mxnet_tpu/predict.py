"""Standalone inference API — the reference's predict-only ABI rebuilt for
TPU (include/mxnet/c_predict_api.h:1-210, src/c_api/c_predict_api.cc).

The reference ships a minimal C surface (MXPredCreate / MXPredSetInput /
MXPredForward / MXPredGetOutput / MXPredReshape) so mobile/amalgamation
builds can run a trained model without the full framework.  Here the same
lifecycle is a small class over the Symbol/Executor stack: create from a
``-symbol.json`` string + ``.params`` blob, set named inputs, run one
jit-compiled XLA forward, read outputs.  Like MXPredCreate, auxiliary
states come from the params blob and the forward runs in inference mode
(is_train=False).

TPU-native notes: the forward is ONE cached XLA program per input-shape
signature — ``reshape`` (MXPredReshape analog) just rebinds, hitting the
jit cache when shapes repeat.  Weights stay device-resident across calls.

Determinism is load-bearing upstream: two replicas serving the same
checkpoint run the same compiled program and return bit-identical
outputs for the same input, which is what lets the fleet tier resend a
keyed request to a DIFFERENT replica (exactly-once retry, hedging —
fleet/router.py) without the client seeing which one answered.
"""
from __future__ import annotations

import io as _pyio

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from .context import current_context

__all__ = ["Predictor", "load_ndarray_file", "create", "export_compiled",
           "load_compiled"]


def load_ndarray_file(blob, ctx=None):
    """Parse a ``.params``-format byte blob -> dict of NDArray
    (MXNDListCreate analog, c_predict_api.h:139-155)."""
    fi = _pyio.BytesIO(blob if isinstance(blob, (bytes, bytearray))
                       else bytes(blob))
    names, arrays = nd._load_stream(fi, ctx)
    if names:
        return dict(zip(names, arrays))
    return {str(i): a for i, a in enumerate(arrays)}


def _strip_prefix(params):
    """Split a checkpoint dict with ``arg:``/``aux:`` prefixes (the
    save_checkpoint convention, python/mxnet/model.py) into (args, auxs)."""
    args, auxs = {}, {}
    for k, v in params.items():
        if k.startswith("arg:"):
            args[k[4:]] = v
        elif k.startswith("aux:"):
            auxs[k[4:]] = v
        else:
            args[k] = v
    return args, auxs


class Predictor(object):
    """Inference-only executor with the MXPred lifecycle
    (c_predict_api.h:43-137: Create/SetInput/Forward/GetOutput/Reshape)."""

    def __init__(self, symbol_json, param_blob, input_shapes, ctx=None,
                 output_name=None):
        if isinstance(symbol_json, sym.Symbol):
            net = symbol_json
        else:
            net = sym.load_json(symbol_json)
        if output_name is not None:
            # MXPredCreatePartialOut analog: predict up to a named output
            net = net.get_internals()[output_name]
        self._sym = net
        self._ctx = ctx if ctx is not None else current_context()
        if isinstance(param_blob, dict):
            params = param_blob
        else:
            params = load_ndarray_file(param_blob, self._ctx)
        self._arg_params, self._aux_params = _strip_prefix(params)
        self._inputs = {}
        self._exec = None
        self._exec_cache = {}  # shape signature -> bound Executor
        self.reshape(dict(input_shapes))

    def reshape(self, input_shapes):
        """Rebind for new input shapes (MXPredReshape, c_predict_api.h:107).
        Weights are reused; executors are cached per shape signature so a
        repeated signature reuses its compiled XLA program instead of
        recompiling.  Staged inputs are cleared — like MXPredReshape,
        inputs must be re-set afterwards."""
        self._inputs = {}
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        signature = tuple(sorted(self._input_shapes.items()))
        cached = self._exec_cache.get(signature)
        if cached is not None:
            self._exec = cached
            return self
        arg_names = self._sym.list_arguments()
        unknown = [n for n in self._input_shapes if n not in arg_names]
        if unknown:
            raise MXNetError("input name(s) %s not in symbol arguments"
                             % (unknown,))
        kwargs = dict(self._input_shapes)
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**kwargs)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in self._input_shapes:
                args[name] = nd.zeros(shape, ctx=self._ctx)
            elif name in self._arg_params:
                if tuple(self._arg_params[name].shape) != tuple(shape):
                    raise MXNetError(
                        "param %s shape mismatch: file %s vs inferred %s"
                        % (name, self._arg_params[name].shape, shape))
                args[name] = self._arg_params[name]
            else:
                # args absent from the blob (e.g. loss labels at inference)
                # are allocated, not errors — c_predict_api.cc:190-196
                args[name] = nd.zeros(shape, ctx=self._ctx)
        auxs = {}
        for name, shape in zip(self._sym.list_auxiliary_states(),
                               aux_shapes):
            if name in self._aux_params:
                auxs[name] = self._aux_params[name]
            else:
                auxs[name] = nd.zeros(shape, ctx=self._ctx)
        self._exec = self._sym.bind(self._ctx, args, args_grad=None,
                                    grad_req="null", aux_states=auxs)
        self._exec_cache[signature] = self._exec
        return self

    def set_params(self, param_blob):
        """Hot-swap the device-resident parameter VALUES in place.

        ``reshape`` hands each cached executor the very NDArray objects
        held in ``_arg_params``/``_aux_params`` (no copy — see the bind
        above), and ``Executor.forward`` re-reads those buffers on every
        call.  Swapping ``._data`` therefore lands the new weights in
        EVERY cached bucket executor at once, between forwards, with no
        re-bind and no recompile (same shapes + dtypes = the same jitted
        program).  This is the device-level half of the serving hot-swap
        contract (``serving/deploy.py``): an in-flight forward keeps the
        arrays it already read, the next forward sees the new epoch.

        Names must be a subset of the loaded set and shapes/dtypes must
        match exactly — anything else is a different PROGRAM, which is a
        restart, not a swap."""
        params = param_blob if isinstance(param_blob, dict) \
            else load_ndarray_file(param_blob, self._ctx)
        new_args, new_auxs = _strip_prefix(params)
        for cur, new, what in ((self._arg_params, new_args, "arg"),
                               (self._aux_params, new_auxs, "aux")):
            for name, v in new.items():
                old = cur.get(name)
                if old is None:
                    raise MXNetError(
                        "set_params: unknown %s %r (not in the bound "
                        "parameter set)" % (what, name))
                new_nd = v if isinstance(v, nd.NDArray) \
                    else nd.array(np.asarray(v), ctx=self._ctx,
                                  dtype=np.asarray(v).dtype)
                if tuple(new_nd.shape) != tuple(old.shape) or \
                        np.dtype(new_nd.dtype) != np.dtype(old.dtype):
                    raise MXNetError(
                        "set_params: %s %r is %s/%s, bound set holds "
                        "%s/%s — a shape/dtype change needs a rebind, "
                        "not a swap" % (what, name, new_nd.shape,
                                        new_nd.dtype, old.shape,
                                        old.dtype))
                old._data = new_nd._data
        return self

    def set_input(self, name, data):
        """MXPredSetInput: stage a named input for the next forward."""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %r (declared: %s)"
                             % (name, sorted(self._input_shapes)))
        data = np.asarray(data, dtype=np.float32)
        if tuple(data.shape) != self._input_shapes[name]:
            raise MXNetError("input %r shape %s != declared %s"
                             % (name, data.shape, self._input_shapes[name]))
        self._inputs[name] = data
        return self

    def forward(self, **inputs):
        """MXPredForward: run one inference-mode forward pass."""
        for name, data in inputs.items():
            self.set_input(name, data)
        missing = set(self._input_shapes) - set(self._inputs)
        if missing:
            raise MXNetError("inputs not set: %s" % sorted(missing))
        self._exec.forward(is_train=False, **self._inputs)
        return self

    def get_output(self, index=0):
        """MXPredGetOutput: fetch output ``index`` as numpy."""
        return self._exec.outputs[index].asnumpy()

    @property
    def output_names(self):
        return self._sym.list_outputs()


def create(symbol_json, param_blob, input_shapes, ctx=None,
           output_name=None):
    """MXPredCreate analog."""
    return Predictor(symbol_json, param_blob, input_shapes, ctx,
                     output_name)


# ---------------------------------------------------------------------------
# Portable compiled export — the amalgamation analog
# ---------------------------------------------------------------------------

def export_compiled(symbol, arg_params, aux_params, input_shapes,
                    fname=None, platforms=None):
    """Serialize the inference function (graph + baked-in weights) as a
    portable StableHLO artifact via ``jax.export``.

    The reference ships models to phones/JS by amalgamating the predict
    path into one self-contained file (amalgamation/README.md:1-13 +
    mxnet_predict.py).  The TPU-native analog: one serialized artifact
    holding the lowered computation AND the weights, loadable by any
    process with jax installed — no mxnet_tpu needed (see
    :func:`load_compiled`).

    input_shapes: {input_name: shape}.  Returns the bytes (also written to
    ``fname`` when given).  ``platforms`` defaults to ("cpu", "tpu") so
    one artifact serves both (multi-platform StableHLO lowering).

    CALLING CONVENTION: the exported callable takes the inputs as
    positional arrays in ``sorted(input_shapes)`` name order (load_compiled
    documents the same contract).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport
    from .executor import _build_eval
    from .ndarray import NDArray

    if not isinstance(symbol, sym.Symbol):
        symbol = sym.load_json(symbol)
    eval_fn = _build_eval(symbol)

    def _raw(d):
        return {k: (v._data if isinstance(v, NDArray) else jnp.asarray(v))
                for k, v in (d or {}).items()}

    params = _raw(arg_params)
    auxs = _raw(aux_params)
    input_names = sorted(input_shapes)
    rng = jax.random.PRNGKey(0)

    unknown = [n for n in input_shapes
               if n not in set(symbol.list_arguments())]
    if unknown:
        raise MXNetError("input name(s) %s not in symbol arguments"
                         % (unknown,))
    # loss labels / aux states absent from both inputs and the param dicts:
    # zeros, the Predictor.reshape allocation rule
    shapes = {k: tuple(v) for k, v in input_shapes.items()}
    arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
    for name, shp in zip(symbol.list_arguments(), arg_shapes):
        if name not in params and name not in shapes:
            params[name] = jnp.zeros(shp, jnp.float32)
    for name, shp in zip(symbol.list_auxiliary_states(), aux_shapes):
        if name not in auxs:
            auxs[name] = jnp.zeros(shp, jnp.float32)

    def infer(*inputs):
        merged = dict(params)
        merged.update(dict(zip(input_names, inputs)))
        outs, _ = eval_fn(merged, auxs, rng, False)
        return tuple(outs)

    specs = [jax.ShapeDtypeStruct(shapes[n], jnp.float32)
             for n in input_names]
    exported = jexport.export(
        jax.jit(infer),
        platforms=tuple(platforms) if platforms else ("cpu", "tpu"))(*specs)
    blob = exported.serialize()
    if fname:
        with open(fname, "wb") as f:
            f.write(bytes(blob))
    return bytes(blob)


def load_compiled(blob_or_fname):
    """Load an :func:`export_compiled` artifact -> callable(*inputs),
    inputs positional in sorted-input-name order (the export contract).

    Needs only jax (the artifact embeds graph + weights) — the mobile/
    embedded deployment contract of the reference's amalgamated build.
    """
    import os as _os
    from jax import export as jexport
    if isinstance(blob_or_fname, (str, _os.PathLike)):
        with open(blob_or_fname, "rb") as f:
            blob = f.read()
    else:
        blob = blob_or_fname
    exported = jexport.deserialize(bytearray(blob))
    return exported.call
