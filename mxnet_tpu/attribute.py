"""Attribute scoping (reference python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='dev1'):`` annotates symbols created inside the
scope — the mechanism behind manual model parallelism (reference
example/model-parallel-lstm/lstm.py:48-99; the PlaceDevice pass consumes
ctx_group, src/executor/graph_executor.cc:242-331).  In this framework
ctx_group maps to mesh/device assignment at bind time (see parallel/).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]


class AttrScope(object):
    _state = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be strings")
        self._attr = kwargs
        self._old_scope = None

    def get(self, attr):
        """Merge the scope's attrs into ``attr`` (user attrs win)."""
        if not self._attr:
            return attr or {}
        ret = self._attr.copy()
        if attr:
            ret.update(attr)
        return ret

    def __enter__(self):
        if not hasattr(AttrScope._state, "current"):
            AttrScope._state.current = AttrScope()
        self._old_scope = AttrScope._state.current
        merged = self._old_scope._attr.copy()
        merged.update(self._attr)
        self._attr = merged
        AttrScope._state.current = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._state.current = self._old_scope


def current():
    if not hasattr(AttrScope._state, "current"):
        AttrScope._state.current = AttrScope()
    return AttrScope._state.current
