"""Executor — compiled graph execution.

Re-design of the reference GraphExecutor (src/executor/graph_executor.cc,
1,126 LoC).  Where the reference builds an NNVM fwd+bwd graph, plans memory,
and pushes per-op engine tasks, this executor traces the Symbol DAG into one
pure JAX function and jits it:

- graph building + gradient: ``jax.vjp`` over the traced function
  (nnvm::pass::Gradient analog; mirroring/remat is ``jax.checkpoint`` at the
  model level).
- memory planning / pooled reuse: XLA's buffer assignment.
- bulk segments & cached ops (InitCachedOps/InitOpSegs,
  graph_executor.cc:556,690): the whole graph IS one fused XLA program.

Training dispatch is a single fused fwd+bwd+aux-update XLA call per batch:
``forward(is_train=True)`` computes outputs, gradients (w.r.t. args whose
grad_req != 'null', with ones head-gradients — the loss-layer convention) and
BatchNorm-style aux updates in one compiled program; ``backward()`` then just
writes the cached gradients into the grad arrays (honoring write/add).
``backward(out_grads)`` with explicit head gradients re-runs the same
compiled function with those heads.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import random as _random
from .base import MXNetError, register_env

ENV_BACKWARD_DO_MIRROR = register_env(
    "MXNET_BACKWARD_DO_MIRROR", default=0,
    doc="1 = memory mirror mode: the backward rematerializes activations "
        "per checkpoint segment instead of storing them")
ENV_MIRROR_SEGMENTS = register_env(
    "MXNET_MIRROR_SEGMENTS",
    doc="Segment count for mirror mode (default sqrt of op count)")
from .context import Context, current_context
from .ndarray import NDArray, zeros as nd_zeros
from .ops.registry import OpDef

__all__ = ["Executor"]


def _filter_attrs(op, attrs):
    """Keep only attrs the op function accepts (graph nodes also carry
    framework attrs like ctx_group / lr_mult).  Unknown USER attrs were
    already rejected at symbol-creation time (OpDef.validate_attrs)."""
    from .ops.registry import fn_signature_info
    names, has_var_kw = fn_signature_info(op.fn)
    if has_var_kw:
        return dict(attrs)
    return {k: v for k, v in attrs.items() if k in names}


def _node_plan(symbol):
    """Precompute the per-node execution plan for the trace.  Slot 5 is
    the node's position in this graph's topological order — the
    per-node RNG fold constant.  It must be a pure function of the GRAPH
    (never of process history): folding the old process-global Symbol
    uid meant the same seeded program drew different Dropout masks
    depending on how many symbols the process had ever created, so a
    test suite's earlier tests silently changed later seeded runs.

    Slot 6 is an optional fusion override, ``None`` or ``(fn,
    extra_refs, eval_dead_ins)``: the interpreter then calls ``fn``
    instead of the node's op, appending the values of ``extra_refs``
    ((src_node, idx) pairs) to the node's own inputs — how the mxfuse
    plan-optimizer passes (:mod:`mxnet_tpu.mxfuse`) rewrite node groups
    without renumbering the plan (RNG fold constants stay put);
    ``eval_dead_ins`` feeds the inference-trace dead-node
    elimination."""
    plan = []
    for ix, node in enumerate(symbol._nodes()):
        if node.is_variable:
            plan.append((node, None, None, None, ix, None))
            continue
        attrs = node.op.normalize_attrs(node.op_attrs())
        call_attrs = _filter_attrs(node.op, attrs)
        n_out = node.op.get_num_outputs(attrs)
        n_in = len(node.op.get_input_names(attrs))
        aux_names = node.op.get_aux_names(attrs)
        aux_var_names = []
        for k in range(len(aux_names)):
            if n_in + k < len(node.inputs):
                src, _ = node.inputs[n_in + k]
                aux_var_names.append(src.name if src.is_variable else None)
        plan.append((node, call_attrs, n_out, aux_var_names, ix, None))
    return plan


def _fuse_bn_plan(plan, out_refs):
    """Run the mxfuse plan-optimizer pipeline (docs/how_to/
    performance.md "The plan optimizer") — kept under the historical
    name as the executor's rewrite entry point.  Entries keep their
    positions (only the override slot changes), so RNG fold constants
    are unchanged and ``MXTPU_FUSED_KERNELS=0`` (which skips the
    pipeline entirely) restores the exact pre-fusion program."""
    from . import mxfuse
    return mxfuse.optimize_plan(plan, out_refs)


def _build_eval(symbol, placement=None, mirror_segments=0):
    """Return eval_fn(args_dict, aux_dict, rng, is_train) ->
    (outputs_list, aux_updates_dict).  Pure — jit/vjp-able.

    ``placement`` (id(node) -> jax device) activates group2ctx model
    parallelism: every node's inputs are committed to its group's device
    before dispatch — the reference's PlaceDevice pass inserting
    _CrossDeviceCopy at group boundaries (graph_executor.cc:242-331),
    expressed as jax.device_put (whose vjp transposes to a device_put of
    the cotangent back across the same boundary).  Placement-active graphs
    run eagerly per-op, the reference's own dispatch model.

    ``mirror_segments`` > 1 wraps the trace in that many jax.checkpoint
    segments: the backward rematerializes each segment's activations
    instead of storing them (the reference's MXNET_BACKWARD_DO_MIRROR
    memory mode, graph_executor.cc InitFullGraph mirror option)."""
    plan = _node_plan(symbol)
    out_refs = [(id(n), i) for n, i in symbol._outputs]
    placement = placement or {}
    # mxfuse plan-optimizer passes (MXTPU_FUSED_KERNELS): fused
    # dispatch only — the placement (eager per-op) path and monitored
    # runs keep the plain plan, so per-node taps still see the unfused
    # node outputs
    fused_plan = plan if placement else _fuse_bn_plan(plan, out_refs)
    # the inference-trace pass set (infer_trace): dead-node elimination
    # + bind-time constant folding over the EVAL interpretation only —
    # entries are skipped, never changed, so positions (RNG folds,
    # monitor coordinates) are untouched and values are bit-identical
    # (dead entries were unread; folded values are computed once here
    # instead of per trace)
    infer_plan, const_env = None, {}
    if not placement:
        from .kernels import fused_enabled
        if fused_enabled("infer_trace"):
            from . import mxfuse
            const_env, infer_plan = mxfuse.fold_constants(
                mxfuse.live_entries(fused_plan, out_refs))
    if mirror_segments and mirror_segments > 1:
        if placement:
            import logging
            logging.warning(
                "MXNET_BACKWARD_DO_MIRROR ignored: group2ctx placement "
                "runs per-op eagerly, which jax.checkpoint cannot wrap")
        else:
            return _build_eval_segmented(plan, fused_plan, out_refs,
                                         int(mirror_segments))

    if not placement:
        def eval_fn(args, aux, rng, is_train, monitor=None):
            if monitor is not None:
                chunk = plan              # plain: every node tapped
            elif not is_train and infer_plan is not None:
                chunk = infer_plan        # pruned + const-folded eval
            else:
                chunk = fused_plan
            env = dict(const_env) if chunk is infer_plan else {}
            aux_updates = {}
            _run_plan_nodes(chunk, env, args, aux, rng, is_train,
                            aux_updates, monitor)
            return [env[nid][i] for nid, i in out_refs], aux_updates
        return eval_fn

    def eval_fn(args, aux, rng, is_train, monitor=None):
        env = {}
        aux_updates = {}
        for node, call_attrs, n_out, aux_var_names, rng_ix, _ov in plan:
            dev = placement.get(id(node))
            if node.op is None:
                if node.name in args:
                    val = args[node.name]
                elif node.name in aux:
                    val = aux[node.name]
                else:
                    raise MXNetError("unbound variable %r" % node.name)
                if dev is not None:
                    val = jax.device_put(val, dev)
                env[id(node)] = (val,)
                continue
            ins = [env[id(src)][idx] for src, idx in node.inputs]
            if dev is not None:
                ins = [jax.device_put(x, dev) for x in ins]
            kw = {}
            if node.op.needs_is_train:
                kw["is_train"] = is_train
            if node.op.needs_rng:
                kw["rng"] = jax.random.fold_in(rng, rng_ix)
            with jax.named_scope(node.name):
                out = node.op.fn(*ins, **call_attrs, **kw)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            env[id(node)] = tuple(out[:n_out])
            for name, arr in zip(aux_var_names, out[n_out:]):
                if name is not None:
                    aux_updates[name] = arr
            if monitor is not None:
                monitor(node, env[id(node)])
        outputs = [env[nid][i] for nid, i in out_refs]
        return outputs, aux_updates

    return eval_fn


def mirror_segments_for(symbol, force=False):
    """Segment count for the memory-mirror mode (0 = off).  Engages when
    MXNET_BACKWARD_DO_MIRROR=1 (or ``force``, the SPMDTrainer remat
    param); MXNET_MIRROR_SEGMENTS overrides the sqrt-of-op-count
    default."""
    from .base import get_env
    if not force and str(get_env(ENV_BACKWARD_DO_MIRROR, "0")) != "1":
        return 0
    n_ops = sum(1 for nd_ in symbol._nodes() if nd_.op is not None)
    return max(2, int(get_env(ENV_MIRROR_SEGMENTS,
                              int(np.sqrt(max(1, n_ops))))))


def _run_plan_nodes(chunk, env, args, aux, rng, is_train, aux_updates,
                    monitor=None):
    """Interpret a slice of the node plan against ``env`` (id -> outputs
    tuple).  Shared by the plain and segmented eval builders."""
    for node, call_attrs, n_out, aux_var_names, rng_ix, override in chunk:
        if node.op is None:
            if node.name in args:
                val = args[node.name]
            elif node.name in aux:
                val = aux[node.name]
            else:
                raise MXNetError("unbound variable %r" % node.name)
            env[id(node)] = (val,)
            continue
        kw = {}
        if node.op.needs_is_train or override is not None:
            # override bodies ALWAYS receive is_train (train/eval
            # lowering choices are theirs to make), whatever the
            # underlying op declares
            kw["is_train"] = is_train
        if node.op.needs_rng:
            kw["rng"] = jax.random.fold_in(rng, rng_ix)
        if override is not None:
            # fusion override (mxfuse passes): fn replaces the op, with
            # the referenced extra inputs appended (conv data/weights).
            # Inputs the override declared dead on the inference path
            # ride as None — their producers may have been pruned from
            # the eval trace by infer_trace (the fn ignores them there)
            fn, extra_refs = override[0], override[1]
            dead = override[2] if len(override) > 2 and not is_train \
                else ()
            ins = [None if pos in dead else env[id(src)][idx]
                   for pos, (src, idx) in enumerate(node.inputs)]
            for src, idx in extra_refs:
                if id(src) not in env and src.op is None:
                    # variable extras may sit LATER in plan order than
                    # this entry (a merged group references every
                    # sibling's weights) — bind them on first touch
                    if src.name in args:
                        env[id(src)] = (args[src.name],)
                    elif src.name in aux:
                        env[id(src)] = (aux[src.name],)
                    else:
                        raise MXNetError("unbound variable %r"
                                         % src.name)
                ins.append(env[id(src)][idx])
        else:
            fn = node.op.fn
            ins = [env[id(src)][idx] for src, idx in node.inputs]
        # named_scope stamps the symbol node name into HLO op_name
        # metadata, so device profiles attribute fused-program time back
        # to graph nodes (reference per-op profiler semantics,
        # src/engine/profiler.cc AddOprStat with opr_name)
        with jax.named_scope(node.name):
            out = fn(*ins, **call_attrs, **kw)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        env[id(node)] = tuple(out[:n_out])
        for name, arr in zip(aux_var_names, out[n_out:]):
            if name is not None:
                aux_updates[name] = arr
        if monitor is not None:
            monitor(node, env[id(node)])


def _build_eval_segmented(plan, fused_plan, out_refs, n_segments):
    """Segmented-remat eval: the plan is split into ~n_segments chunks,
    each wrapped in jax.checkpoint.  Residuals between segments are only
    the live boundary values, so activation memory scales with the segment
    size while the backward recomputes within each segment.  Monitored
    (per-op tap) runs interpret the plain ``plan``; everything else runs
    the (possibly BN-fused) ``fused_plan`` — same node positions, so the
    liveness analysis below serves both."""
    n = len(fused_plan)
    seg_size = max(1, -(-n // n_segments))
    chunks = [fused_plan[i:i + seg_size] for i in range(0, n, seg_size)]

    # liveness: which node outputs cross each boundary
    produced_in = {}
    for ci, chunk in enumerate(chunks):
        for node, *_ in chunk:
            produced_in[id(node)] = ci
    consumers = {}   # id -> last chunk index that reads it
    for ci, chunk in enumerate(chunks):
        for entry in chunk:
            node, override = entry[0], entry[5]
            if node.op is not None:
                refs = list(node.inputs)
                if override is not None:
                    refs += list(override[1])   # fusion extra inputs
                for src, _idx in refs:
                    consumers[id(src)] = max(consumers.get(id(src), -1), ci)
    for nid, _ in out_refs:
        consumers[nid] = len(chunks)
    live_out = []   # per chunk: ids leaving that boundary, ordered
    for ci in range(len(chunks)):
        ids = [nid for nid, pc in produced_in.items()
               if pc <= ci and consumers.get(nid, -1) > ci]
        live_out.append(ids)

    def eval_fn(args, aux, rng, is_train, monitor=None):
        if monitor is not None:
            # monitored (per-op tap) runs use the plain interpretation
            env, aux_updates = {}, {}
            _run_plan_nodes(plan, env, args, aux, rng, is_train,
                            aux_updates, monitor)
            return [env[nid][i] for nid, i in out_refs], aux_updates

        aux_updates = {}
        carry_ids = []
        carry_vals = ()

        for ci, chunk in enumerate(chunks):
            ids_in = list(carry_ids)
            ids_out = live_out[ci]

            def seg(vals_in, args, aux, rng, _chunk=chunk, _in=ids_in,
                    _out=ids_out):
                env = dict(zip(_in, vals_in))
                seg_aux = {}
                _run_plan_nodes(_chunk, env, args, aux, rng, is_train,
                                seg_aux)
                return tuple(env[i] for i in _out), seg_aux

            out_vals, seg_aux = jax.checkpoint(seg)(carry_vals, args, aux,
                                                    rng)
            aux_updates.update(seg_aux)
            carry_ids, carry_vals = ids_out, out_vals

        env = dict(zip(carry_ids, carry_vals))
        outputs = [env[nid][i] for nid, i in out_refs]
        return outputs, aux_updates

    return eval_fn


class Executor(object):
    """Bound, compiled executor (parity: python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        self._symbol = symbol
        self._ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None
        self._monitor_all = False

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_dict = _to_dict("args", args, arg_names, self._ctx)
        self.aux_dict = _to_dict("aux_states", aux_states, aux_names,
                                 self._ctx, allow_missing=not aux_names)
        self.grad_req = _req_dict(grad_req, arg_names)
        if args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = _to_dict("args_grad", args_grad, arg_names,
                                      self._ctx, allow_missing=True)
        self._diff_names = tuple(
            n for n in arg_names
            if self.grad_req.get(n, "null") != "null" and n in self.grad_dict)

        # group2ctx model parallelism: resolve each node's ctx_group to a
        # device; active only when ≥2 distinct devices result (a single
        # device degenerates to the normal fused path)
        placement = {}
        if self._group2ctx:
            for node in symbol._nodes():
                grp = node.attrs.get("ctx_group")
                c = self._group2ctx.get(grp) if grp else None
                placement[id(node)] = (c if c is not None
                                       else self._ctx).jax_device
            if len(set(placement.values())) <= 1:
                placement = {}
        self._placement = placement

        self._eval = _build_eval(symbol, placement=placement or None,
                                 mirror_segments=mirror_segments_for(symbol))
        # graphs holding host-callback ops (Custom) can only be whole-graph
        # jitted if the backend supports callbacks under jit; otherwise run
        # eagerly — the reference likewise executes CustomOp host-side
        # between kernel launches (src/operator/custom/custom-inl.h).
        # Multi-device group2ctx placement also runs eagerly: one XLA
        # program compiles for one device, while eager ops dispatch on
        # their (committed) input devices.
        has_no_jit = any(n.op is not None and getattr(n.op, "no_jit", False)
                         for n in symbol._nodes())
        from .ops.registry import callbacks_under_jit_supported
        use_jit = (not has_no_jit or callbacks_under_jit_supported()) \
            and not placement
        _maybe_jit = jax.jit if use_jit else (lambda f: f)
        self._jit_fwd = _maybe_jit(
            lambda a, x, r: self._eval(a, x, r, False)[0])
        self._jit_fwd_train = _maybe_jit(
            lambda a, x, r: self._eval(a, x, r, True))
        diff_names = self._diff_names

        # memory mirror mode lives inside self._eval (segmented
        # jax.checkpoint, see _build_eval_segmented)

        def train_fn(args, aux, rng, heads):
            diff = {k: args[k] for k in diff_names}
            rest = {k: v for k, v in args.items() if k not in diff}

            def f(d):
                merged = dict(rest)
                merged.update(d)
                outs, auxu = self._eval(merged, aux, rng, True)
                return tuple(outs), auxu

            outs, vjp_fn, auxu = jax.vjp(f, diff, has_aux=True)
            grads, = vjp_fn(tuple(heads))
            return list(outs), grads, auxu

        self._jit_train = _maybe_jit(train_fn)

        self._outputs = None      # list[NDArray]
        self._grads = None        # dict name -> jax array
        self._head_cache = {}     # arg-shape signature -> ones head grads

    # -- construction helpers --------------------------------------------
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req="write", type_dict=None,
                     group2ctx=None, shared_exec=None, shapes=None):
        arg_shapes, _, aux_shapes = symbol.infer_shape(**(shapes or {}))
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_types, _, aux_types = symbol.infer_type(
            **{k: v for k, v in (type_dict or {}).items()})
        args = {}
        for name, shape, typ in zip(arg_names, arg_shapes, arg_types):
            args[name] = nd_zeros(shape, ctx=ctx, dtype=np.dtype(typ))
        aux = {}
        for name, shape, typ in zip(aux_names, aux_shapes, aux_types):
            aux[name] = nd_zeros(shape, ctx=ctx, dtype=np.dtype(typ))
        req = _req_dict(grad_req, arg_names)
        grads = {name: nd_zeros(shape, ctx=ctx)
                 for name, shape in zip(arg_names, arg_shapes)
                 if req.get(name, "null") != "null"}
        return Executor(symbol, ctx, args, args_grad=grads, grad_req=grad_req,
                        aux_states=aux, group2ctx=group2ctx,
                        shared_exec=shared_exec)

    # -- dict/list views ---------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._symbol.list_auxiliary_states()]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -- execution ---------------------------------------------------------
    def _raw(self, d):
        return {k: v._data for k, v in d.items()}

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown forward argument %r" % k)
            if isinstance(v, NDArray):
                self.arg_dict[k]._data = v._data
            else:
                self.arg_dict[k][:] = v
        rng = _random.next_key()
        self._last_rng = rng
        args, aux = self._raw(self.arg_dict), self._raw(self.aux_dict)

        if self._monitor_callback is not None:
            return self._forward_monitored(args, aux, rng, is_train)

        if is_train and self._diff_names:
            heads = self._ones_heads()
            outs, grads, auxu = self._jit_train(args, aux, rng, heads)
            self._grads = grads
        elif is_train:
            outs, auxu = self._jit_fwd_train(args, aux, rng)
            self._grads = None
        else:
            outs = self._jit_fwd(args, aux, rng)
            auxu = {}
            self._grads = None
        self._outputs = [NDArray._from_jax(o) for o in outs]
        if is_train:
            self._apply_aux(auxu)
        return self._outputs

    def _ones_heads(self):
        sig = tuple(sorted((k, v.shape) for k, v in self.arg_dict.items()))
        heads = self._head_cache.get(sig)
        if heads is None:
            _, out_shapes, _ = self._symbol.infer_shape_partial(
                **{k: v.shape for k, v in self.arg_dict.items()})
            heads = [jnp.ones(s if s is not None else (), dtype=jnp.float32)
                     for s in out_shapes]
            self._head_cache[sig] = heads
        return heads

    def _apply_aux(self, auxu):
        for name, arr in auxu.items():
            if name in self.aux_dict:
                self.aux_dict[name]._data = arr

    #: ops whose backward supplies its OWN head gradient (their custom
    #: vjp ignores the incoming cotangent) — the reference's loss layers,
    #: which need no entry in a user-passed out_grads list
    _SELF_GRAD_OPS = frozenset((
        "MakeLoss", "make_loss", "SoftmaxOutput", "softmax_output",
        "LinearRegressionOutput", "MAERegressionOutput",
        "LogisticRegressionOutput", "SVMOutput", "BlockGrad", "stop_gradient",
    ))

    def _pad_out_grads(self, heads):
        """Match user heads to outputs the way the reference does: loss
        outputs (self-gradient ops, incl. need_top_grad=False Customs)
        are skipped; the given heads fill the remaining outputs in
        order; anything left unmatched gets zeros
        (reference graph_executor head_grad binding for the
        Module.backward(out_grads) contract, e.g. the
        parallel_actor_critic example's [log_policy, value] heads next
        to a MakeLoss entropy term and a BlockGrad output)."""
        n_out = len(self._symbol._outputs)
        if len(heads) == n_out:
            return heads
        # zero cotangents must match each output's exact aval: prefer the
        # freshest forward outputs (shape AND dtype); fall back to
        # inferred shapes at float32
        if self._outputs is not None and len(self._outputs) == n_out:
            out_avals = [(o._data.shape, o._data.dtype)
                         for o in self._outputs]
        else:
            _, out_shapes, _ = self._symbol.infer_shape_partial(
                **{k: v.shape for k, v in self.arg_dict.items()})
            out_avals = [(s or (), jnp.float32) for s in out_shapes]
        it = iter(heads)
        full = []
        for (node, _idx), (shape, dtype) in zip(self._symbol._outputs,
                                                out_avals):
            op_name = getattr(node.op, "name", None) if node.op else None
            self_grad = op_name in self._SELF_GRAD_OPS
            if op_name == "Custom":
                from .operator import _prop_for
                try:
                    self_grad = not _prop_for(node.attrs).need_top_grad_
                except Exception:  # noqa: BLE001 — unknown op_type
                    self_grad = False
            if self_grad:
                full.append(jnp.zeros(shape, dtype))
            else:
                g = next(it, None)
                full.append(jnp.zeros(shape, dtype) if g is None else g)
        leftover = list(it)
        if leftover:
            raise MXNetError(
                "backward: %d out_grads given but only %d outputs "
                "accept head gradients" % (len(heads),
                                           len(heads) - len(leftover)))
        return full

    def backward(self, out_grads=None):
        """Write gradients into grad arrays.  Uses the cached fused-step
        gradients when called without explicit head gradients."""
        if not self._diff_names:
            return
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                     for g in out_grads]
            heads = self._pad_out_grads(heads)
            args, aux = self._raw(self.arg_dict), self._raw(self.aux_dict)
            # reuse the forward pass's RNG key so stochastic ops (Dropout,
            # rrelu) see the same masks the observed outputs were computed
            # with — otherwise the gradients would belong to a different
            # sampled forward
            rng = getattr(self, "_last_rng", None)
            if rng is None:
                rng = _random.next_key()
                self._last_rng = rng
            outs, grads, _auxu = self._jit_train(args, aux, rng, heads)
            self._outputs = [NDArray._from_jax(o) for o in outs]
            self._grads = grads
        if self._grads is None:
            # forward(is_train=True) was not called — run the fused step now
            args, aux = self._raw(self.arg_dict), self._raw(self.aux_dict)
            rng = _random.next_key()
            self._last_rng = rng
            outs, grads, auxu = self._jit_train(args, aux, rng,
                                                self._ones_heads())
            self._outputs = [NDArray._from_jax(o) for o in outs]
            self._grads = grads
            self._apply_aux(auxu)
        for name in self._diff_names:
            garr = self.grad_dict[name]
            g = self._grads[name].astype(garr._data.dtype)
            if self.grad_req[name] == "add":
                garr._data = garr._data + g
            else:
                garr._data = g

    @property
    def outputs(self):
        if self._outputs is None:
            self.forward()
        return self._outputs

    # -- monitored (eager) execution for mx.mon.Monitor --------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-node output tap (reference
        MXExecutorSetMonitorCallback / graph_executor.cc:69-72).  Runs the
        graph eagerly (unfused) while installed."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    def _forward_monitored(self, args, aux, rng, is_train):
        taps = []

        monitor_all = self._monitor_all

        def monitor(node, outs):
            names = ([node.name + "_output"] if len(outs) == 1 else
                     ["%s_output%d" % (node.name, i) for i in range(len(outs))])
            for nm, arr in zip(names, outs):
                taps.append((nm, arr))

        if monitor_all:
            for name, arr in {**aux, **args}.items():
                taps.append((name, arr))

        outs, auxu = self._eval(args, aux, rng, is_train, monitor=monitor)
        self._outputs = [NDArray._from_jax(o) for o in outs]
        if is_train:
            self._apply_aux(auxu)
        self._grads = None
        for nm, arr in taps:
            self._monitor_callback(nm, NDArray._from_jax(arr))
        return self._outputs

    # -- misc ---------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        from .ndarray import _to_device
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                dst = self.arg_dict[name]
                dst._data = _to_device(arr._data.astype(dst._data.dtype),
                                       dst._ctx)
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    dst = self.aux_dict[name]
                    dst._data = _to_device(arr._data.astype(dst._data.dtype),
                                           dst._ctx)
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **new_shapes):
        """Return a new executor for new input shapes, sharing parameter
        arrays (executor.py:reshape).  Recompilation is handled by jit."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        arg_names = self._symbol.list_arguments()
        new_args, new_grads = {}, {}
        for name, shape in zip(arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if cur.shape == tuple(shape):
                new_args[name] = cur
                if name in self.grad_dict:
                    new_grads[name] = self.grad_dict[name]
            else:
                if name not in new_shapes and not partial_shaping:
                    raise MXNetError(
                        "reshape changes the shape of parameter %r from %s to "
                        "%s; pass partial_shaping=True to allow reallocating "
                        "it (contents are NOT preserved)"
                        % (name, cur.shape, tuple(shape)))
                new_args[name] = nd_zeros(shape, ctx=self._ctx)
                if name in self.grad_dict:
                    new_grads[name] = nd_zeros(shape, ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args,
                        args_grad=new_grads or None, grad_req=self.grad_req,
                        aux_states=self.aux_dict, group2ctx=self._group2ctx)

    def debug_str(self):
        lines = ["Symbol Outputs:"]
        for name in self._symbol.list_outputs():
            lines.append("\toutput[%s]" % name)
        for node in self._symbol._nodes():
            if node.is_variable:
                lines.append("Variable:%s" % node.name)
            else:
                ins = ", ".join(s.name for s, _ in node.inputs)
                lines.append("Op:%s, Name=%s\n\tInputs:\n\t\t%s"
                             % (node.op.name, node.name, ins))
        return "\n".join(lines)


def _to_dict(what, values, names, ctx, allow_missing=False):
    if values is None:
        if allow_missing:
            return {}
        raise MXNetError("%s must be provided" % what)
    if isinstance(values, dict):
        out = {}
        for name in names:
            if name in values:
                v = values[name]
                out[name] = v if isinstance(v, NDArray) else NDArray(v, ctx=ctx)
            elif not allow_missing:
                raise MXNetError("%s: missing entry %r" % (what, name))
        return out
    values = list(values)
    if len(values) != len(names):
        raise MXNetError("%s: length mismatch (%d given, %d needed: %s)"
                         % (what, len(values), len(names), names))
    return {n: (v if isinstance(v, NDArray) else NDArray(v, ctx=ctx))
            for n, v in zip(names, values) if v is not None}


def _req_dict(grad_req, arg_names):
    if isinstance(grad_req, str):
        return {n: grad_req for n in arg_names}
    if isinstance(grad_req, (list, tuple)):
        return dict(zip(arg_names, grad_req))
    if isinstance(grad_req, dict):
        return {n: grad_req.get(n, "null") for n in arg_names}
    raise MXNetError("invalid grad_req %r" % (grad_req,))


def _executor_close(self):
    """Release this executor's compiled programs and the buffers it owns
    (its outputs), and drop its references to the bound arrays (reference
    ~GraphExecutor frees its memory pool; jax buffers otherwise wait for
    GC and retained jit wrappers pin executables).  The bound
    arg/grad/aux arrays are CALLER-owned — they may be shared with other
    executors (shared_exec bucketing) or still be the caller's parameter
    NDArrays — so close() must not delete them, only unpin them.  The
    executor is unusable afterwards; safe to call twice."""
    # On the eager (non-jit) path a passthrough graph output can BE one of
    # the caller's bound arrays (identity, not a copy) — deleting it would
    # invalidate a caller-owned buffer, so collect bound identities first.
    bound = set()
    for d in (self.arg_dict, self.aux_dict, self.grad_dict):
        for arr in (d or {}).values():
            data = getattr(arr, "_data", None)
            if isinstance(data, jax.Array):
                bound.add(id(data))
    for o in (self._outputs or []):
        data = getattr(o, "_data", None)
        if isinstance(data, jax.Array) and id(data) not in bound:
            try:
                data.delete()
            except Exception:  # noqa: BLE001
                pass
    self._outputs = None
    self.arg_dict = {}
    self.aux_dict = {}
    self.grad_dict = {}
    for attr in ("_jit_fwd", "_jit_fwd_train", "_jit_train"):
        fn = getattr(self, attr, None)
        if fn is not None and hasattr(fn, "clear_cache"):
            try:
                fn.clear_cache()
            except Exception:  # noqa: BLE001
                pass
        setattr(self, attr, None)
    self._eval = None
    import gc
    gc.collect()


Executor.close = _executor_close
Executor.__enter__ = lambda self: self
Executor.__exit__ = (
    lambda self, exc_type, exc_val, exc_tb: (self.close(), False)[1])
