"""Model helpers: checkpointing + the kvstore update trio + legacy
FeedForward API (reference python/mxnet/model.py, 936 LoC)."""
from __future__ import annotations

import logging
import time
from collections import namedtuple

import numpy as np

from . import io as mxio
from . import kvstore as kvs
from . import metric as metric_mod
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .context import cpu, current_context
from .initializer import Uniform
from .ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide kvstore + update_on_kvstore policy (reference model.py:40-77)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore) or hasattr(kvstore, "push"):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and kvstore != "tpu":
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(p.shape) for p in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """kv.init each param; pull initial weights if updating on kvstore
    (reference model.py:79-86)."""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """push grads / pull weights (reference model.py:88-97)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        kvstore.push(index, grad_list, priority=-index)
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None):
    """aggregate grads (via kvstore if given) and run the local updater per
    device (reference model.py:99-123)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    blocking=None):
    """Save prefix-symbol.json + prefix-%04d.params (reference
    model.py:save_checkpoint; format matches the reference byte-for-byte
    via ndarray.save).  Files land via temp + fsync + rename so a crash
    mid-save can never tear an existing checkpoint.

    ``blocking=False`` (default: the ``MXTPU_CKPT_ASYNC`` env) returns
    after snapshotting the params to host copies; the shared background
    :class:`~mxnet_tpu.resilience.CheckpointWriter` then serializes and
    writes — drain with ``resilience.wait_checkpoints()``.  ``symbol``
    may be a Symbol or an already-serialized JSON string (what async
    snapshots and CheckpointManager's writer hand in)."""
    from .resilience import (atomic_path, atomic_write, checkpoint_async,
                             snapshot_params, submit_checkpoint)
    sym_json = symbol if isinstance(symbol, str) or symbol is None \
        else symbol.tojson()
    if blocking is None:
        blocking = not checkpoint_async()
    if not blocking:
        arg_params = snapshot_params(arg_params)
        aux_params = snapshot_params(aux_params)

    def _write():
        if sym_json is not None:
            atomic_write("%s-symbol.json" % prefix, sym_json)
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        param_name = "%s-%04d.params" % (prefix, epoch)
        with atomic_path(param_name) as tmp:
            nd.save(tmp, save_dict)
        logging.info("Saved checkpoint to \"%s\"", param_name)

    if blocking:
        _write()
    else:
        submit_checkpoint(_write, "%s epoch %d" % (prefix, epoch))


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (reference
    model.py:load_checkpoint)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Legacy pre-Module training API (reference model.py:FeedForward).
    Implemented as a thin adapter over mx.mod.Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=Uniform(0.01), numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [current_context()]
        if not isinstance(self.ctx, (list, tuple)):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _get_module(self, data):
        from .module import Module
        label_names = [d.name for d in (data.provide_label or [])] or None
        mod = Module(self.symbol, data_names=[d.name for d in data.provide_data],
                     label_names=label_names, context=self.ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data = self._init_iter(X, y, is_train=True)
        self._module = self._get_module(data)
        self._module.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore,
                         optimizer=self.optimizer,
                         optimizer_params=self.kwargs,
                         initializer=self.initializer,
                         arg_params=self.arg_params,
                         aux_params=self.aux_params,
                         begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch, monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            self._module = self._get_module(data)
            self._module.bind([(d.name, d.shape) for d in data.provide_data],
                              None, for_training=False)
            self._module.set_params(self.arg_params or {},
                                    self.aux_params or {},
                                    allow_missing=False)
        if reset:
            data.reset()
        outputs = self._module.predict(data, num_batch=num_batch)
        if isinstance(outputs, list):
            return [o.asnumpy() for o in outputs]
        return outputs.asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        data = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            self._module = self._get_module(data)
            self._module.bind([(d.name, d.shape) for d in data.provide_data],
                              [(l.name, l.shape) for l in data.provide_label],
                              for_training=False)
            self._module.set_params(self.arg_params or {},
                                    self.aux_params or {})
        res = self._module.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]

    def _init_iter(self, X, y, is_train):
        if isinstance(X, (np.ndarray, NDArray)):
            batch = min(self.numpy_batch_size, len(X))
            return mxio.NDArrayIter(X, y, batch_size=batch, shuffle=is_train,
                                    last_batch_handle="roll_over" if is_train
                                    else "pad")
        return X

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=Uniform(0.01), eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list)
        return model
